"""Bundled ZMTP 3.0 peer — PUB/SUB over TCP without libzmq/pyzmq, the way
io/mqtt_native.py bundles MQTT 3.1.1 (the reference links pebbe/zmq4 ->
libzmq; this image has neither, and the wire protocol is small).

Implements the subset the zmq connector needs (ZMTP/3.0 spec,
rfc.zeromq.org/spec/23):

- 64-byte greeting (signature / version 3.0 / NULL mechanism)
- NULL security handshake (READY command with Socket-Type metadata,
  PUB<->SUB compatibility check)
- framing: short/long frames, MORE and COMMAND flags, multipart messages
- SUB subscriptions as 0x01/0x00-prefixed messages (3.0 style), honored
  PUB-side with prefix matching per peer
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.infra import EngineError, logger

_FLAG_MORE = 0x01
_FLAG_LONG = 0x02
_FLAG_CMD = 0x04

_COMPAT = {"PUB": {"SUB"}, "SUB": {"PUB"}}


def _greeting() -> bytes:
    sig = b"\xff" + b"\x00" * 8 + b"\x7f"
    mechanism = b"NULL" + b"\x00" * 16
    return sig + bytes([3, 0]) + mechanism + b"\x00" + b"\x00" * 31


def _ready(socket_type: str) -> bytes:
    """READY command frame body: name + metadata (Socket-Type)."""
    name = b"\x05READY"
    key = b"Socket-Type"
    val = socket_type.encode()
    meta = bytes([len(key)]) + key + struct.pack(">I", len(val)) + val
    return name + meta


class ZmtpPeer:
    """One handshaked ZMTP connection."""

    def __init__(self, sock: socket.socket, socket_type: str) -> None:
        self.sock = sock
        self.socket_type = socket_type
        self.peer_type = ""
        self._rbuf = b""
        self._wlock = threading.Lock()

    # ------------------------------------------------------------ handshake
    def handshake(self, timeout: float = 10.0) -> None:
        self.sock.settimeout(timeout)
        self.sock.sendall(_greeting())
        g = self._read_n(64)
        if g[0] != 0xFF or g[9] != 0x7F:
            raise EngineError("zmq: bad ZMTP signature")
        if g[10] < 3:
            raise EngineError(f"zmq: peer speaks ZMTP {g[10]}.x, need >= 3")
        mech = g[12:32].rstrip(b"\x00").decode()
        if mech != "NULL":
            raise EngineError(f"zmq: unsupported mechanism {mech}")
        self.send_frame(_ready(self.socket_type), cmd=True)
        flags, body = self.recv_frame()
        if not flags & _FLAG_CMD or not body.startswith(b"\x05READY"):
            raise EngineError("zmq: expected READY command")
        self.peer_type = self._parse_socket_type(body[6:])
        if self.peer_type not in _COMPAT.get(self.socket_type, set()):
            raise EngineError(
                f"zmq: socket types incompatible: {self.socket_type} <-> "
                f"{self.peer_type or '?'}")
        self.sock.settimeout(None)

    @staticmethod
    def _parse_socket_type(meta: bytes) -> str:
        pos = 0
        while pos < len(meta):
            nlen = meta[pos]
            name = meta[pos + 1:pos + 1 + nlen]
            pos += 1 + nlen
            vlen = struct.unpack(">I", meta[pos:pos + 4])[0]
            val = meta[pos + 4:pos + 4 + vlen]
            pos += 4 + vlen
            if name.lower() == b"socket-type":
                return val.decode()
        return ""

    # -------------------------------------------------------------- framing
    def send_frame(self, body: bytes, more: bool = False,
                   cmd: bool = False) -> None:
        flags = (_FLAG_MORE if more else 0) | (_FLAG_CMD if cmd else 0)
        if len(body) > 255:
            hdr = bytes([flags | _FLAG_LONG]) + struct.pack(">Q", len(body))
        else:
            hdr = bytes([flags, len(body)])
        with self._wlock:
            self.sock.sendall(hdr + body)

    def send_multipart(self, parts: List[bytes]) -> None:
        with self._wlock:
            out = b""
            for i, p in enumerate(parts):
                flags = _FLAG_MORE if i < len(parts) - 1 else 0
                if len(p) > 255:
                    out += bytes([flags | _FLAG_LONG]) \
                        + struct.pack(">Q", len(p)) + p
                else:
                    out += bytes([flags, len(p)]) + p
            self.sock.sendall(out)

    def recv_frame(self) -> Tuple[int, bytes]:
        """Resumable across socket timeouts: nothing is consumed from the
        read buffer until the WHOLE frame is present, so an idle-poll
        timeout can never desync the stream."""
        while True:
            buf = self._rbuf
            if len(buf) >= 1:
                flags = buf[0]
                hdr = 9 if flags & _FLAG_LONG else 2
                if len(buf) >= hdr:
                    if flags & _FLAG_LONG:
                        size = struct.unpack(">Q", buf[1:9])[0]
                    else:
                        size = buf[1]
                    if size > 256 * 1024 * 1024:
                        raise EngineError(f"zmq: frame of {size} bytes refused")
                    if len(buf) >= hdr + size:
                        body = buf[hdr:hdr + size]
                        self._rbuf = buf[hdr + size:]
                        return flags, bytes(body)
            chunk = self.sock.recv(4096)
            if not chunk:
                raise ConnectionError("zmq: peer closed")
            self._rbuf += chunk

    def recv_multipart(self) -> List[bytes]:
        """Next data message (commands are handled/skipped). A socket
        timeout before the FIRST frame propagates (idle poll); once a
        message started, continuation frames retry through timeouts so a
        multipart is never torn."""
        while True:
            flags, body = self.recv_frame()
            if flags & _FLAG_CMD:
                continue  # PING etc. — NULL mechanism needs no reply here
            parts = [body]
            while flags & _FLAG_MORE:
                try:
                    flags, body = self.recv_frame()
                except socket.timeout:
                    continue
                parts.append(body)
            return parts

    def _read_n(self, n: int) -> bytes:
        while len(self._rbuf) < n:
            chunk = self.sock.recv(max(4096, n - len(self._rbuf)))
            if not chunk:
                raise ConnectionError("zmq: peer closed")
            self._rbuf += chunk
        out, self._rbuf = self._rbuf[:n], self._rbuf[n:]
        return out

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def _parse_endpoint(server: str) -> Tuple[str, int]:
    if not server.startswith("tcp://"):
        raise EngineError(f"zmq: only tcp:// endpoints supported: {server}")
    host, _, port = server[6:].partition(":")
    if host in ("*", ""):  # canonical zmq wildcard bind form
        host = "0.0.0.0"
    try:
        return host, int(port)
    except ValueError:
        raise EngineError(f"zmq: endpoint needs a numeric port: {server}")


class PubServer:
    """PUB socket: binds, handshakes subscribers, honors their prefix
    subscriptions (0x01 subscribe / 0x00 unsubscribe messages)."""

    def __init__(self, server: str) -> None:
        host, port = _parse_endpoint(server)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._peers: Dict[ZmtpPeer, List[bytes]] = {}  # peer -> prefixes
        # every accepted socket, including ones still mid-handshake — close()
        # must kill those too or a half-open orphan pins the port (its
        # handshake read blocks up to 10s after the listener is gone)
        self._accepted: List[socket.socket] = []
        self._mu = threading.Lock()
        self._stop = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="zmq-pub-accept").start()

    def _accept_loop(self) -> None:
        # short-poll accept instead of a fully blocking one: a thread parked
        # deep in accept() survives close() (the syscall pins the kernel
        # listener as a port-squatting zombie) and is exposed to fd-reuse
        # races; with a 250ms poll every such window is bounded
        self._srv.settimeout(0.25)
        while not self._stop.is_set():
            try:
                sock, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._mu:
                if self._stop.is_set():
                    sock.close()
                    return
                self._accepted.append(sock)
            threading.Thread(target=self._serve_peer, args=(sock,),
                             daemon=True).start()

    def _serve_peer(self, sock: socket.socket) -> None:
        peer = ZmtpPeer(sock, "PUB")
        try:
            peer.handshake()
        except Exception as e:
            logger.warning("zmq pub: handshake failed: %s", e)
            peer.close()
            with self._mu:
                try:
                    self._accepted.remove(sock)
                except ValueError:
                    pass  # close() already drained the list
            return
        # send-only timeout: a wedged subscriber must not block publish
        # (recv stays blocking — the subscription loop below needs it)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                        struct.pack("ll", 5, 0))
        with self._mu:
            self._peers[peer] = []
        try:
            while not self._stop.is_set():
                msg = peer.recv_multipart()
                if not msg or not msg[0]:
                    continue
                op, prefix = msg[0][0], msg[0][1:]
                with self._mu:
                    subs = self._peers.get(peer)
                    if subs is None:
                        return
                    if op == 1:
                        if prefix not in subs:  # idle probes re-subscribe
                            subs.append(prefix)
                    elif op == 0 and prefix in subs:
                        subs.remove(prefix)
        except (ConnectionError, OSError, EngineError):
            pass
        finally:
            with self._mu:
                self._peers.pop(peer, None)
                try:
                    self._accepted.remove(sock)
                except ValueError:
                    pass  # close() already drained the list
            peer.close()

    def subscriber_count(self) -> int:
        with self._mu:
            return len(self._peers)

    def send(self, parts: List[bytes]) -> None:
        """Deliver to every subscriber whose prefix matches the first
        frame (PUB drops when no one matches — zmq semantics)."""
        head = parts[0] if parts else b""
        with self._mu:
            targets = [p for p, subs in self._peers.items()
                       if any(head.startswith(s) for s in subs)]
        for p in targets:
            try:
                p.send_multipart(parts)
            except OSError:
                with self._mu:
                    self._peers.pop(p, None)
                p.close()

    def close(self) -> None:
        self._stop.set()
        try:
            # abort the accept thread's blocked accept(): merely closing
            # the fd does NOT interrupt it on Linux — the in-flight syscall
            # keeps a zombie listener squatting the port until some
            # connection happens to wake it
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        with self._mu:
            peers = list(self._peers)
            self._peers.clear()
            accepted = list(self._accepted)
            self._accepted.clear()
        # abortive close (RST, not FIN): a graceful close parks the
        # accepted sockets in FIN_WAIT until every subscriber notices,
        # keeping the port unbindable across a quick PUB restart
        for s in accepted:
            try:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
            except OSError:
                pass
        for p in peers:
            p.close()
        for s in accepted:
            try:
                s.close()
            except OSError:
                pass


class SubClient:
    """SUB socket: connects, subscribes to a topic prefix, and feeds
    received messages to a callback; redials on connection loss."""

    def __init__(self, server: str, topic: str,
                 on_message: Callable[[List[bytes]], None]) -> None:
        self.host, self.port = _parse_endpoint(server)
        self.topic = topic.encode()
        self.on_message = on_message
        self._stop = threading.Event()
        self._peer: Optional[ZmtpPeer] = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="zmq-sub")
        self._thread.start()

    def _run(self) -> None:
        # jittered exponential redial (utils/backoff.py) — a fleet of
        # SUBs must not stampede a restarting publisher in lockstep
        from ..utils.backoff import Backoff

        backoff = Backoff(base_s=0.1, cap_s=5.0)
        while not self._stop.is_set():
            try:
                # pre-bind the source port: an unbound connect() retried
                # against a dead listener on an ephemeral-range port can TCP
                # simultaneous-open onto ITSELF, squatting the port so the
                # real peer can never bind it again. With an explicit source
                # bind, a dead target just refuses.
                sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                sock.bind(("", 0))
                if sock.getsockname()[1] == self.port:
                    sock.close()
                    raise ConnectionError("source port collided with target")
                sock.settimeout(5)
                sock.connect((self.host, self.port))
                peer = ZmtpPeer(sock, "SUB")
                peer.handshake()
                peer.send_frame(b"\x01" + self.topic)  # subscribe
                self._peer = peer
                backoff.reset()
                # idle probe: every few quiet seconds re-send the
                # (idempotent) subscription — a torn-down peer turns the
                # send into an error and triggers the reconnect path, and a
                # subscribe frame lost in a reconnect race gets replayed
                sock.settimeout(3.0)
                while not self._stop.is_set():
                    try:
                        msg = peer.recv_multipart()
                    except socket.timeout:
                        peer.send_frame(b"\x01" + self.topic)
                        continue
                    self.on_message(msg)
            except Exception as e:
                if self._stop.is_set():
                    return
                logger.debug("zmq sub: reconnect after: %s", e)
                if self._peer is not None:
                    self._peer.close()
                    self._peer = None
                if backoff.wait(self._stop):
                    return

    def close(self) -> None:
        self._stop.set()
        if self._peer is not None:
            self._peer.close()
        self._thread.join(timeout=3)
