"""Redis source/sink/lookup (analogue of the reference's
internal/io/redis: redis sink, redisSub pub/sub source, redis lookup).

No redis client library is assumed: a minimal RESP2 client over a TCP
socket covers the command surface the connectors need (AUTH/SELECT/GET/SET/
LPUSH/RPUSH/PUBLISH/SUBSCRIBE/HGETALL/PING). Values are JSON-encoded on
write and JSON-decoded on read, matching the reference's json payloads.
"""
from __future__ import annotations

import json
import socket
import threading
from typing import Any, Callable, Dict, List, Optional

from ..utils.infra import EngineError, logger
from .contract import LookupSource, Sink, Source


class RespClient:
    """Minimal RESP2 protocol client."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 password: str = "", db: int = 0, timeout: float = 5.0) -> None:
        self.host, self.port = host, port
        self.password, self.db = password, db
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._lock = threading.Lock()

    def connect(self) -> None:
        with self._lock:
            self._connect_locked()

    def _connect_locked(self) -> None:
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout)
        self._buf = b""
        # AUTH/SELECT inline (command() would re-take the non-reentrant lock)
        if self.password:
            self._sock.sendall(self._encode(["AUTH", self.password]))
            self.read_reply()
        if self.db:
            self._sock.sendall(self._encode(["SELECT", str(self.db)]))
            self.read_reply()

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    # ---------------------------------------------------------------- wire
    @staticmethod
    def _encode(args) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(b), b))
        return b"".join(out)

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise EngineError("redis connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise EngineError("redis connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2:]
        return data

    def read_reply(self) -> Any:
        line = self._read_line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise EngineError(f"redis error: {rest.decode()}")
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            return None if n < 0 else self._read_exact(n)
        if t == b"*":
            n = int(rest)
            return None if n < 0 else [self.read_reply() for _ in range(n)]
        raise EngineError(f"redis protocol error: {line!r}")

    def command(self, *args) -> Any:
        with self._lock:
            if self._sock is None:
                self._connect_locked()
            self._sock.sendall(self._encode(args))
            return self.read_reply()

    def send(self, *args) -> None:
        """Send without reading a reply (subscribe stream)."""
        with self._lock:
            if self._sock is None:
                self._connect_locked()
            self._sock.sendall(self._encode(args))


def _client_from_props(props: Dict[str, Any]) -> RespClient:
    addr = props.get("addr", "127.0.0.1:6379")
    if "://" in addr:
        addr = addr.split("://", 1)[1]
    host, _, port = addr.partition(":")
    return RespClient(
        host or "127.0.0.1", int(port or 6379),
        password=props.get("password", ""), db=int(props.get("db", 0)),
        timeout=float(props.get("timeout", 5000)) / 1000.0,
    )


def _decode_value(raw: Any) -> Any:
    if isinstance(raw, (bytes, bytearray)):
        raw = raw.decode("utf-8", errors="replace")
    try:
        return json.loads(raw)
    except (ValueError, TypeError):
        return {"data": raw}


class RedisSubSource(Source):
    """Pub/sub source: SUBSCRIBE to the datasource channels (comma
    separated), ingest every published message (reference redisSub)."""

    def __init__(self) -> None:
        self.channels: List[str] = []
        self.props: Dict[str, Any] = {}
        self._cli: Optional[RespClient] = None
        self._stop = threading.Event()

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        chans = datasource or props.get("channels", "")
        self.channels = [c.strip() for c in str(chans).split(",") if c.strip()]
        if not self.channels:
            raise EngineError("redisSub requires channels (datasource)")
        self.props = props

    def open(self, ingest) -> None:
        self._stop.clear()
        threading.Thread(target=self._loop, args=(ingest,), daemon=True,
                         name="redis-sub").start()

    def _loop(self, ingest) -> None:
        from ..utils.backoff import Backoff

        bo = Backoff(base_s=0.5, cap_s=30.0)
        while not self._stop.is_set():
            try:
                cli = _client_from_props(self.props)
                cli.connect()
                # a subscription idles indefinitely between messages — the
                # command timeout must not tear the connection down
                cli._sock.settimeout(None)
                self._cli = cli
                cli.send("SUBSCRIBE", *self.channels)
                bo.reset()
                while not self._stop.is_set():
                    reply = cli.read_reply()
                    if isinstance(reply, list) and len(reply) >= 3 and \
                            reply[0] in (b"message", "message"):
                        ingest(_decode_value(reply[2]))
            except Exception as exc:
                if self._stop.is_set():
                    return
                logger.warning("redisSub reconnect: %s", exc)
                if bo.wait(self._stop):
                    return

    def close(self) -> None:
        self._stop.set()
        if self._cli is not None:
            self._cli.close()


class RedisSink(Sink):
    """Writes results to redis: datatype string (SET key val) or list
    (LPUSH/RPUSH), key from a field or a static key; optionally PUBLISH to
    a channel instead (reference redis sink options)."""

    def __init__(self) -> None:
        self.props: Dict[str, Any] = {}
        self._cli: Optional[RespClient] = None

    def configure(self, props: Dict[str, Any]) -> None:
        self.props = props
        if not (props.get("key") or props.get("field")
                or props.get("channel")):
            raise EngineError("redis sink requires key, field, or channel")

    def connect(self) -> None:
        self._cli = _client_from_props(self.props)
        self._cli.connect()

    def collect(self, item: Any) -> None:
        rows = item if isinstance(item, list) else [item]
        for row in rows:
            data = row if isinstance(row, str) else json.dumps(row)
            channel = self.props.get("channel")
            if channel:
                self._cli.command("PUBLISH", channel, data)
                continue
            key = self.props.get("key") or (
                row.get(self.props["field"]) if isinstance(row, dict) else None)
            if key is None:
                raise EngineError(
                    f"redis sink: field {self.props.get('field')!r} missing")
            if self.props.get("dataType", "string") == "list":
                cmd = ("RPUSH" if self.props.get("rowkindField") == "append"
                       else "LPUSH")
                self._cli.command(cmd, key, data)
            else:
                args = ["SET", key, data]
                if self.props.get("expiration"):
                    args += ["EX", str(int(self.props["expiration"]))]
                self._cli.command(*args)

    def close(self) -> None:
        if self._cli is not None:
            self._cli.close()


class RedisLookupSource(LookupSource):
    """Lookup by key: GET (json value) or HGETALL per the dataType prop."""

    def __init__(self) -> None:
        self.props: Dict[str, Any] = {}
        self._cli: Optional[RespClient] = None

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.props = dict(props)
        if datasource:
            self.props.setdefault("db", datasource)

    def open(self) -> None:
        self._cli = _client_from_props(self.props)
        self._cli.connect()

    def lookup(self, fields, keys, values) -> List[Dict[str, Any]]:
        if not values:
            return []
        key = str(values[0])
        if self.props.get("dataType") == "hash":
            raw = self._cli.command("HGETALL", key)
            if not raw:
                return []
            it = iter(raw)
            return [{k.decode(): _decode_value(v) for k, v in zip(it, it)}]
        raw = self._cli.command("GET", key)
        if raw is None:
            return []
        val = _decode_value(raw)
        return [val if isinstance(val, dict) else {"value": val}]

    def close(self) -> None:
        if self._cli is not None:
            self._cli.close()
