"""SQL database source/sink/lookup (analogue of the reference's
extensions/sql plugin family: sqlsource, sqlsink, sql lookup).

The driver seam is DB-API 2.0: any module exposing connect() works. The
bundled driver is sqlite3 (stdlib) via url "sqlite://<path>"; other
databases plug in through the `driver` prop naming an importable DB-API
module plus a `dsn` (the reference gates its many drivers behind build tags
the same way).

Source: polls `SELECT ... ` every `interval` ms. With a `trackingColumn`
(indexedField in the reference) only rows beyond the last seen value are
fetched, and the offset participates in rewind (Rewindable contract).
"""
from __future__ import annotations

import re
import threading
from typing import Any, Dict, List, Optional

from ..utils.infra import EngineError, logger
from .contract import LookupSource, Sink, Source

# SQL identifiers (table/column names) are interpolated into statements —
# placeholders cannot quote identifiers — so every one of them, including
# ones derived from UNTRUSTED stream row keys, must match this pattern.
_IDENT = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _check_ident(name: str, what: str) -> str:
    """Validate a (possibly schema-qualified, e.g. public.readings)
    identifier; raises EngineError on anything else."""
    ok = (isinstance(name, str) and name
          and all(_IDENT.match(p) for p in name.split(".")))
    if not ok:
        raise EngineError(f"sql io: invalid {what} identifier {name!r}")
    return name


def _connect(props: Dict[str, Any]):
    url = props.get("url", "")
    if url.startswith("sqlite://"):
        import sqlite3

        conn = sqlite3.connect(url[len("sqlite://"):], check_same_thread=False)
        conn.row_factory = sqlite3.Row
        return conn, "?"
    driver = props.get("driver", "")
    if not driver:
        raise EngineError(
            "sql io requires url 'sqlite://<path>' or a DB-API `driver` "
            "module name + `dsn`")
    import importlib

    mod = importlib.import_module(driver)
    return mod.connect(props.get("dsn", "")), props.get("paramstyle", "%s")


def _rows_to_dicts(cur, rows) -> List[Dict[str, Any]]:
    names = [d[0] for d in cur.description or []]
    out = []
    for row in rows:
        try:
            out.append(dict(row))  # sqlite3.Row supports mapping
        except (TypeError, ValueError):
            out.append(dict(zip(names, row)))
    return out


class SqlSource(Source):
    """Polling query source with optional incremental tracking column."""

    def __init__(self) -> None:
        self.props: Dict[str, Any] = {}
        self.query = ""
        self.interval_ms = 1000
        self.tracking: str = ""
        self._offset: Any = None
        self._stop = threading.Event()
        self._conn = None

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.props = props
        table = datasource or props.get("table", "")
        if table:
            _check_ident(table, "table")
        self.query = props.get("query") or (f"SELECT * FROM {table}"
                                            if table else "")
        # user-supplied queries may end in WHERE/GROUP BY/ORDER BY/LIMIT —
        # the tracking predicate must wrap them as a subselect to compose;
        # only the table form we generated ourselves can take a plain append
        self._wrap_query = bool(props.get("query"))
        if not self.query:
            raise EngineError("sql source requires a table or query")
        self.interval_ms = int(props.get("interval", 1000))
        self.tracking = props.get("trackingColumn", "")
        if self.tracking:
            _check_ident(self.tracking, "trackingColumn")
        self._offset = props.get("startValue")

    def open(self, ingest) -> None:
        self._stop.clear()
        threading.Thread(target=self._loop, args=(ingest,), daemon=True,
                         name="sql-source").start()

    def _loop(self, ingest) -> None:
        conn, ph = None, "?"
        while not self._stop.is_set():
            try:
                if conn is None:
                    conn, ph = _connect(self.props)
                    self._conn = conn
                q, args = self.query, ()
                if self.tracking:
                    order = f" ORDER BY {self.tracking}"
                    if self._offset is not None:
                        if self._wrap_query:
                            q = (f"SELECT * FROM ({q}) AS __ek_sub "
                                 f"WHERE {self.tracking} > {ph}" + order)
                        else:
                            q += (f" WHERE {self.tracking} > {ph}" + order)
                        args = (self._offset,)
                    elif self._wrap_query:
                        q = f"SELECT * FROM ({q}) AS __ek_sub" + order
                    else:
                        q += order
                cur = conn.cursor()
                cur.execute(q, args)
                rows = _rows_to_dicts(cur, cur.fetchall())
                if rows:
                    if self.tracking:
                        self._offset = rows[-1].get(self.tracking,
                                                    self._offset)
                    ingest(rows)
            except Exception as exc:
                if self._stop.is_set():
                    return
                logger.warning("sql source poll error: %s", exc)
                conn = None
            self._stop.wait(self.interval_ms / 1000.0)

    # Rewindable (io/contract.py)
    def get_offset(self) -> Any:
        return self._offset

    def rewind(self, offset: Any) -> None:
        self._offset = offset

    def close(self) -> None:
        self._stop.set()
        if self._conn is not None:
            try:
                self._conn.close()
            except Exception:
                pass


class SqlSink(Sink):
    """INSERTs result rows into a table; columns from the row keys (or the
    `fields` prop for a fixed column list)."""

    def __init__(self) -> None:
        self.props: Dict[str, Any] = {}
        self.table = ""
        self._conn = None
        self._ph = "?"

    def configure(self, props: Dict[str, Any]) -> None:
        self.props = props
        self.table = props.get("table", "")
        if not self.table:
            raise EngineError("sql sink requires a table")
        _check_ident(self.table, "table")
        for f in props.get("fields") or []:
            _check_ident(f, "field")

    def connect(self) -> None:
        self._conn, self._ph = _connect(self.props)

    def collect(self, item: Any) -> None:
        rows = item if isinstance(item, list) else [item]
        fields = self.props.get("fields")
        cur = self._conn.cursor()
        for row in rows:
            if not isinstance(row, dict):
                raise EngineError("sql sink rows must be objects")
            if fields:
                cols = fields
            else:
                # row keys come off the stream (MQTT/websocket/...): they
                # are UNTRUSTED and get interpolated as identifiers — drop
                # any non-conforming key instead of building injectable SQL
                cols = [k for k in row.keys()
                        if isinstance(k, str) and _IDENT.match(k)]
                dropped = len(row) - len(cols)
                if dropped:
                    logger.warning(
                        "sql sink: dropped %d non-identifier row keys", dropped)
                if not cols:
                    continue
            placeholders = ", ".join([self._ph] * len(cols))
            cur.execute(
                f"INSERT INTO {self.table} ({', '.join(cols)}) "
                f"VALUES ({placeholders})",
                tuple(row.get(c) for c in cols))
        self._conn.commit()

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()


class SqlLookupSource(LookupSource):
    def __init__(self) -> None:
        self.props: Dict[str, Any] = {}
        self.table = ""
        self._conn = None
        self._ph = "?"

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.props = props
        self.table = datasource or props.get("table", "")
        if not self.table:
            raise EngineError("sql lookup requires a table")
        _check_ident(self.table, "table")

    def open(self) -> None:
        self._conn, self._ph = _connect(self.props)

    def lookup(self, fields, keys, values) -> List[Dict[str, Any]]:
        where = " AND ".join(
            f"{_check_ident(k, 'lookup key')} = {self._ph}" for k in keys)
        sel = (", ".join(_check_ident(f, "field") for f in fields)
               if fields else "*")
        cur = self._conn.cursor()
        cur.execute(
            f"SELECT {sel} FROM {self.table}"
            + (f" WHERE {where}" if where else ""),
            tuple(values))
        return _rows_to_dicts(cur, cur.fetchall())

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
