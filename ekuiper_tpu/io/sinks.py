"""Basic sinks — log and nop (analogue internal/io/sink/log_sink.go, nop)."""
from __future__ import annotations

import json
from typing import Any, Dict

from ..utils.infra import logger
from .contract import Sink


class LogSink(Sink):
    def __init__(self) -> None:
        self.prefix = "sink result"

    def configure(self, props: Dict[str, Any]) -> None:
        self.prefix = props.get("prefix", self.prefix)

    def collect(self, item: Any) -> None:
        logger.info("%s: %s", self.prefix, json.dumps(item, default=str))


class NopSink(Sink):
    # columnar results may be collected as-is: converting a wide window
    # emission to per-row dicts just to discard it costs seconds of GIL at
    # high-fan-out boundaries (ref: plugins/sinks/nop discards likewise)
    accepts_batches = True

    def __init__(self) -> None:
        self.log = False

    def configure(self, props: Dict[str, Any]) -> None:
        self.log = bool(props.get("log", False))

    def collect(self, item: Any) -> None:
        if self.log:
            logger.debug("nop sink: %s", item)
