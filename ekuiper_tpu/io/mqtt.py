"""MQTT source & sink — analogue of eKuiper's internal/io/mqtt (paho v4/v5
clients with a refcounted shared connection, pkg/connection/conn.go:28-137).

Uses paho-mqtt when installed; otherwise the bundled native MQTT 3.1.1
client (io/mqtt_native.py, same subset API) — MQTT must work out of the
box, it is the reference's flagship ingest protocol.
"""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, Optional, Tuple

try:
    import paho.mqtt.client as mqtt
except ImportError:
    from . import mqtt_native as mqtt

from ..utils.infra import EngineError, logger
from .contract import Sink, Source
from .converters import get_converter

# shared refcounted connections keyed by (server, client_id) —
# pkg/connection pool analogue
_pool: Dict[Tuple[str, str], Tuple[mqtt.Client, int]] = {}
_pool_lock = threading.Lock()


def _acquire(server: str, client_id: str, username: str = "", password: str = "") -> mqtt.Client:
    key = (server, client_id)
    with _pool_lock:
        entry = _pool.get(key)
        if entry is not None:
            client, refs = entry
            _pool[key] = (client, refs + 1)
            return client
        client = mqtt.Client(client_id=client_id or None)
        if username:
            client.username_pw_set(username, password)
        host, _, port = server.replace("tcp://", "").partition(":")
        client.connect(host, int(port or 1883))
        client.loop_start()
        _pool[key] = (client, 1)
        return client


def _release(server: str, client_id: str) -> None:
    key = (server, client_id)
    with _pool_lock:
        entry = _pool.get(key)
        if entry is None:
            return
        client, refs = entry
        if refs <= 1:
            client.loop_stop()
            client.disconnect()
            del _pool[key]
        else:
            _pool[key] = (client, refs - 1)


class MqttSource(Source):
    def __init__(self) -> None:
        self.topic = ""
        self.server = "tcp://127.0.0.1:1883"
        self.qos = 1
        self.client_id = ""
        self.username = ""
        self.password = ""
        self._client: Optional[mqtt.Client] = None

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.topic = datasource or props.get("topic", "")
        self.server = props.get("server", self.server)
        self.qos = int(props.get("qos", 1))
        self.client_id = props.get("clientid", "")
        self.username = props.get("username", "")
        self.password = props.get("password", "")
        # no format/converter here: the source delivers raw bytes and the
        # SourceNode's stream-level converter decodes (incl. the native
        # columnar fast path)

    def open(self, ingest) -> None:
        def on_message(client, userdata, msg) -> None:
            # deliver RAW bytes: the SourceNode owns the stream's FORMAT
            # converter and, for scalar-typed JSON schemas, batch-decodes
            # buffered payloads straight to columns in C (io/fastjson.py)
            # instead of one python json.loads per MQTT message
            ingest(bytes(msg.payload),
                   {"topic": msg.topic, "qos": msg.qos,
                    "messageId": getattr(msg, "mid", 0)})

        self._client = _acquire(self.server, self.client_id, self.username,
                                self.password)
        self._client.message_callback_add(self.topic, on_message)
        self._client.subscribe(self.topic, qos=self.qos)

    def close(self) -> None:
        if self._client is not None:
            self._client.message_callback_remove(self.topic)
            self._client.unsubscribe(self.topic)
            _release(self.server, self.client_id)
            self._client = None


class MqttSink(Sink):
    def __init__(self) -> None:
        self.topic = ""
        self.server = "tcp://127.0.0.1:1883"
        self.qos = 1
        self.retained = False
        self.client_id = ""
        self.username = ""
        self.password = ""
        self.format = "json"
        self._client: Optional[mqtt.Client] = None

    def configure(self, props: Dict[str, Any]) -> None:
        self.topic = props.get("topic", "")
        self.server = props.get("server", self.server)
        self.qos = int(props.get("qos", 1))
        self.retained = bool(props.get("retained", False))
        self.client_id = props.get("clientid", "")
        self.username = props.get("username", "")
        self.password = props.get("password", "")
        self.format = props.get("format", "json")
        if not self.topic:
            raise EngineError("mqtt sink requires topic")

    def connect(self) -> None:
        self._client = _acquire(self.server, self.client_id, self.username,
                                self.password)

    def collect(self, item: Any) -> None:
        conv = get_converter(self.format)
        payload = item if isinstance(item, (bytes, str)) else conv.encode(item)
        info = self._client.publish(
            self.topic, payload, qos=self.qos, retain=self.retained
        )
        if info.rc != mqtt.MQTT_ERR_SUCCESS:
            raise EngineError(f"mqtt publish failed rc={info.rc}")

    def close(self) -> None:
        if self._client is not None:
            _release(self.server, self.client_id)
            self._client = None
