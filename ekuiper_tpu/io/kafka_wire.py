"""Minimal Kafka wire-protocol client — no kafka-python/librdkafka needed.

The reference treats Kafka as a first-class extension built on segmentio's
kafka-go (extensions/impl/kafka/source.go, sink.go); this image bundles no
Kafka client, so the connector speaks the broker protocol directly over the
engine's own sockets, the same way the MQTT connector bundles a native
3.1.1 client (io/mqtt_native.py).

Implements the RPCs a group-less producer/consumer needs, pinned to
legacy (non-flexible, big-endian) versions every broker since 0.10 serves
(SASL auth is the exception: SaslHandshake v1 + SaslAuthenticate are
KIP-152, broker >= 1.0):

    ApiVersions v0   handshake / liveness
    Metadata    v1   topic -> partition -> leader routing
    ListOffsets v1   earliest/latest offset resolution
    Produce     v2   MessageSet magic=1 (CRC32, timestamps)
    Fetch       v2   MessageSet magic=1 decode (incl. partial trailing entry)

Offsets are managed by the caller (the engine checkpoints them through the
Rewindable contract, io/contract.py) — the consumer-group protocol is
deliberately NOT implemented; see io/kafka_io.py for the divergence note.
"""
from __future__ import annotations

import base64
import gzip
import hashlib
import hmac
import os
import socket
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ..utils.infra import EngineError

_LATEST, _EARLIEST = -1, -2


class KafkaTransportError(EngineError):
    """Connection-level failure (hangup, desync, truncation): the cached
    connection must be dropped and redialed. Distinct from broker-reported
    errors (UNKNOWN_TOPIC, NOT_LEADER, ...), which leave the stream valid."""


class KafkaBrokerError(EngineError):
    """Broker-reported error code. `code` lets callers branch on semantics
    (NOT_LEADER -> refresh routing, OFFSET_OUT_OF_RANGE -> reset policy)."""

    def __init__(self, msg: str, code: int) -> None:
        super().__init__(msg)
        self.code = code


#: broker errors that mean "this broker no longer serves the partition" —
#: the leader cache entry is stale and a metadata refresh can recover
_RETRIABLE_ROUTING = (3, 5, 6)  # UNKNOWN_TOPIC, LEADER_NOT_AVAIL, NOT_LEADER
OFFSET_OUT_OF_RANGE = 1


# ----------------------------------------------------------------- encoding
def _i16(v: int) -> bytes:
    return struct.pack(">h", v)


def _i32(v: int) -> bytes:
    return struct.pack(">i", v)


def _i64(v: int) -> bytes:
    return struct.pack(">q", v)


def _string(s: Optional[str]) -> bytes:
    if s is None:
        return _i16(-1)
    b = s.encode()
    return _i16(len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return _i32(-1)
    return _i32(len(b)) + b


def _array(items: List[bytes]) -> bytes:
    return _i32(len(items)) + b"".join(items)


class _Reader:
    """Cursor over a response body."""

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise KafkaTransportError("kafka: truncated response")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def i8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        return None if n < 0 else self._take(n).decode()

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        return None if n < 0 else self._take(n)

    def remaining(self) -> int:
        return len(self.data) - self.pos


# -------------------------------------------------------------- message set
def encode_message_set(messages: List[Tuple[Optional[bytes], bytes, int]]) -> bytes:
    """messages: [(key, value, timestamp_ms)] -> MessageSet magic=1 bytes.
    Producer-side offsets are placeholders (the broker assigns real ones)."""
    out = []
    for i, (key, value, ts) in enumerate(messages):
        body = (struct.pack(">bb", 1, 0) + _i64(ts) + _bytes(key)
                + _bytes(value))
        crc = zlib.crc32(body) & 0xFFFFFFFF
        msg = struct.pack(">I", crc) + body
        out.append(_i64(i) + _i32(len(msg)) + msg)
    return b"".join(out)


def decode_message_set(
    data: bytes,
) -> List[Tuple[int, Optional[bytes], Optional[bytes], int]]:
    """MessageSet bytes -> [(offset, key, value, timestamp_ms)]. A fetch may
    end with a partially-transferred entry — it is silently dropped (the
    next fetch re-reads it), per protocol. A null value stays None — it is
    a delete tombstone, distinct from an empty b"" payload; the consumer
    decides how to surface it."""
    out: List[Tuple[int, Optional[bytes], Optional[bytes], int]] = []
    pos = 0
    while pos + 12 <= len(data):
        offset, size = struct.unpack(">qi", data[pos:pos + 12])
        if pos + 12 + size > len(data):
            break  # partial trailing message
        r = _Reader(data[pos + 12:pos + 12 + size])
        crc = r.i32() & 0xFFFFFFFF
        body = r.data[r.pos:]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise EngineError(f"kafka: bad message CRC at offset {offset}")
        magic = r.i8()
        attrs = r.i8()
        codec = attrs & 0x07
        ts = r.i64() if magic >= 1 else -1
        key = r.bytes_()
        value = r.bytes_()
        if codec == 0:
            out.append((offset, key, value, ts))
        elif codec == 1 and value is not None:
            # gzip wrapper message: the value is an inner message set whose
            # entries carry relative offsets (magic 1) anchored so the LAST
            # inner message has the wrapper's offset
            inner = decode_message_set(gzip.decompress(value))
            if inner:
                base = offset - inner[-1][0]
                out.extend((base + o, k, v, t) for o, k, v, t in inner)
        else:
            codec_name = {2: "snappy", 3: "lz4", 4: "zstd"}.get(codec, str(codec))
            raise EngineError(
                f"kafka: {codec_name}-compressed message set at offset "
                f"{offset} — only gzip/uncompressed supported; set the "
                "producer's compression.type accordingly")
        pos += 12 + size
    return out


# -------------------------------------------------------------------- scram
def _scram_hash(mech: str):
    return hashlib.sha512 if mech.endswith("512") else hashlib.sha256


def _scram_hi(mech: str, password: bytes, salt: bytes, it: int) -> bytes:
    return hashlib.pbkdf2_hmac(_scram_hash(mech)().name, password, salt, it)


def _scram_client(mech: str, user: str, password: str, step) -> None:
    """RFC 5802 client over a send(payload)->response callable. Verifies
    the server signature — a broker that can't prove knowledge of the
    stored key fails authentication even if it accepts ours."""
    h = _scram_hash(mech)
    c_nonce = base64.b64encode(os.urandom(18)).decode()
    user_sasl = user.replace("=", "=3D").replace(",", "=2C")
    c_first_bare = f"n={user_sasl},r={c_nonce}"
    s_first = step(("n,," + c_first_bare).encode()).decode()
    try:
        attrs = dict(p.split("=", 1) for p in s_first.split(","))
        nonce = attrs["r"]
        salt = base64.b64decode(attrs["s"])
        iters = int(attrs["i"])
    except (ValueError, KeyError) as e:
        raise EngineError(f"kafka: malformed SCRAM server-first message: {e}")
    if not nonce.startswith(c_nonce):
        raise EngineError("kafka: SCRAM server nonce mismatch")
    if not 4096 <= iters <= 10_000_000:
        # floor per RFC 7677 guidance (downgrade protection); ceiling so a
        # rogue broker can't pin the CPU in PBKDF2 for hours inside connect
        raise EngineError(
            f"kafka: SCRAM iteration count {iters} outside [4096, 1e7]")
    salted = _scram_hi(mech, password.encode(), salt, iters)
    client_key = hmac.new(salted, b"Client Key", h).digest()
    stored_key = h(client_key).digest()
    c_final_bare = f"c=biws,r={nonce}"
    auth_msg = f"{c_first_bare},{s_first},{c_final_bare}".encode()
    client_sig = hmac.new(stored_key, auth_msg, h).digest()
    proof = bytes(a ^ b for a, b in zip(client_key, client_sig))
    c_final = f"{c_final_bare},p={base64.b64encode(proof).decode()}"
    s_final = step(c_final.encode()).decode()
    try:
        fattrs = dict(p.split("=", 1) for p in s_final.split(","))
        if "e" in fattrs:
            raise EngineError(f"kafka: SCRAM rejected: {fattrs['e']}")
        server_v = base64.b64decode(fattrs.get("v", ""))
    except EngineError:
        raise
    except (ValueError, KeyError) as e:
        raise EngineError(f"kafka: malformed SCRAM server-final message: {e}")
    server_key = hmac.new(salted, b"Server Key", h).digest()
    server_sig = hmac.new(server_key, auth_msg, h).digest()
    if server_v != server_sig:
        raise EngineError("kafka: SCRAM server signature invalid")


# ------------------------------------------------------------------- client
class _BrokerConn:
    """One TCP connection to one broker; int32-size-framed req/rep."""

    def __init__(self, host: str, port: int, client_id: str,
                 timeout: float) -> None:
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.client_id = client_id
        self.corr = 0
        self.lock = threading.Lock()

    def request(self, api_key: int, api_version: int, body: bytes,
                timeout: Optional[float] = None) -> _Reader:
        with self.lock:
            self.corr += 1
            corr = self.corr
            hdr = (_i16(api_key) + _i16(api_version) + _i32(corr)
                   + _string(self.client_id))
            payload = hdr + body
            if timeout is not None:
                self.sock.settimeout(timeout)
            self.sock.sendall(_i32(len(payload)) + payload)
            raw = self._recv_frame()
        r = _Reader(raw)
        got = r.i32()
        if got != corr:
            raise KafkaTransportError(
                f"kafka: correlation mismatch {got} != {corr}")
        return r

    def _recv_frame(self) -> bytes:
        hdr = self._recv_n(4)
        n = struct.unpack(">i", hdr)[0]
        return self._recv_n(n)

    def _recv_n(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise KafkaTransportError("kafka: broker closed connection")
            buf += chunk
        return buf

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


ERRS = {
    0: "NONE", 1: "OFFSET_OUT_OF_RANGE", 3: "UNKNOWN_TOPIC_OR_PARTITION",
    5: "LEADER_NOT_AVAILABLE", 6: "NOT_LEADER_FOR_PARTITION",
    7: "REQUEST_TIMED_OUT",
}


def _check(code: int, what: str) -> None:
    if code != 0:
        raise KafkaBrokerError(
            f"kafka: {what} failed: {ERRS.get(code, 'error')} ({code})", code)


class KafkaClient:
    """Partition-leader-aware client over one or more bootstrap brokers.

    sasl: optional (mechanism, username, password) with mechanism PLAIN,
    SCRAM-SHA-256 or SCRAM-SHA-512 — authenticated on every broker
    connection via SaslHandshake v1 + SaslAuthenticate v0 round trips
    (reference saslAuthType plain/scram_sha_256/scram_sha_512,
    extensions/impl/kafka/source.go:255). SCRAM is the full RFC 5802
    exchange over hashlib/hmac — no external dependency."""

    def __init__(self, brokers: str, client_id: str = "ekuiper-tpu",
                 timeout: float = 10.0,
                 sasl: Optional[Tuple[str, str, str]] = None) -> None:
        self.bootstrap = [self._hostport(b) for b in brokers.split(",") if b]
        if not self.bootstrap:
            raise EngineError("kafka: brokers can not be empty")
        if sasl is not None and sasl[0].upper() not in (
                "PLAIN", "SCRAM-SHA-256", "SCRAM-SHA-512"):
            raise EngineError(
                f"kafka: unsupported SASL mechanism {sasl[0]!r} "
                "(PLAIN / SCRAM-SHA-256 / SCRAM-SHA-512)")
        self.client_id = client_id
        self.timeout = timeout
        self.sasl = sasl
        self._conns: Dict[Tuple[str, int], _BrokerConn] = {}
        self._leaders: Dict[Tuple[str, int], Tuple[str, int]] = {}
        self._mu = threading.Lock()

    def _authenticate(self, conn: _BrokerConn) -> None:
        """SaslHandshake v1 announces the mechanism, then SaslAuthenticate
        v0 round trips carry the mechanism exchange: one RFC 4616 token
        for PLAIN, the three-message RFC 5802 exchange for SCRAM."""
        mech, user, password = self.sasl
        mech = mech.upper()
        r = conn.request(17, 1, _string(mech))  # SaslHandshake v1
        code = r.i16()
        if code != 0:
            mechs = [r.string() for _ in range(r.i32())]
            raise EngineError(
                f"kafka: SASL handshake failed ({ERRS.get(code, code)}); "
                f"broker offers {mechs}")

        def auth_step(payload: bytes) -> bytes:
            rr = conn.request(36, 0, _bytes(payload))
            c = rr.i16()
            msg = rr.string()
            if c != 0:
                raise EngineError(
                    f"kafka: SASL authentication failed: {msg}")
            return rr.bytes_() or b""

        if mech == "PLAIN":
            auth_step(b"\x00" + user.encode() + b"\x00" + password.encode())
            return
        _scram_client(mech, user, password, auth_step)

    @staticmethod
    def _hostport(b: str) -> Tuple[str, int]:
        host, _, port = b.strip().partition(":")
        return host, int(port or 9092)

    def _conn(self, addr: Tuple[str, int]) -> _BrokerConn:
        with self._mu:
            c = self._conns.get(addr)
        if c is not None:
            return c
        # dial + authenticate OUTSIDE the lock: SASL is two blocking round
        # trips, and holding _mu through them would stall close() and all
        # other routing against a wedged broker
        c = _BrokerConn(addr[0], addr[1], self.client_id, self.timeout)
        if self.sasl is not None:
            try:
                self._authenticate(c)
            except BaseException:
                c.close()
                raise
        with self._mu:
            existing = self._conns.get(addr)
            if existing is not None:  # lost the race — keep the winner
                c.close()
                return existing
            self._conns[addr] = c
        return c

    def _drop_conn(self, addr: Tuple[str, int]) -> None:
        with self._mu:
            c = self._conns.pop(addr, None)
        if c is not None:
            c.close()

    def _any_request(self, api_key: int, api_version: int,
                     body: bytes) -> _Reader:
        """Serve a cluster-level RPC from any bootstrap broker. A transport
        failure drops that broker's cached connection (a dead or desynced
        conn must never poison the client) and tries the next; redial is
        attempted once per broker."""
        err: Optional[Exception] = None
        for addr in self.bootstrap:
            for _ in (0, 1):
                try:
                    return self._conn(addr).request(api_key, api_version, body)
                except (OSError, KafkaTransportError) as e:
                    err = e
                    self._drop_conn(addr)
        raise EngineError(f"kafka: no bootstrap broker reachable: {err}")

    # ------------------------------------------------------------- metadata
    def api_versions(self) -> Dict[int, Tuple[int, int]]:
        r = self._any_request(18, 0, b"")
        _check(r.i16(), "ApiVersions")
        out = {}
        for _ in range(r.i32()):
            k, lo, hi = r.i16(), r.i16(), r.i16()
            out[k] = (lo, hi)
        return out

    def metadata(self, topics: List[str]) -> Dict[str, Dict[int, Tuple[str, int]]]:
        """topic -> partition -> leader (host, port); refreshes the leader
        cache used by produce/fetch routing."""
        body = _array([_string(t) for t in topics])
        r = self._any_request(3, 1, body)
        brokers: Dict[int, Tuple[str, int]] = {}
        for _ in range(r.i32()):
            node = r.i32()
            host = r.string() or ""
            port = r.i32()
            r.string()  # rack
            brokers[node] = (host, port)
        r.i32()  # controller id
        out: Dict[str, Dict[int, Tuple[str, int]]] = {}
        for _ in range(r.i32()):
            terr = r.i16()
            topic = r.string() or ""
            r.i8()  # is_internal
            parts: Dict[int, Tuple[str, int]] = {}
            for _ in range(r.i32()):
                perr = r.i16()
                pid = r.i32()
                leader = r.i32()
                for _ in range(r.i32()):
                    r.i32()  # replicas
                for _ in range(r.i32()):
                    r.i32()  # isr
                if perr == 0 and leader in brokers:
                    parts[pid] = brokers[leader]
            _check(terr, f"Metadata({topic})")
            out[topic] = parts
            with self._mu:
                for pid, addr in parts.items():
                    self._leaders[(topic, pid)] = addr
        return out

    def partitions(self, topic: str) -> List[int]:
        md = self.metadata([topic])
        parts = sorted(md.get(topic, {}))
        if not parts:
            raise EngineError(f"kafka: topic {topic} has no available partitions")
        return parts

    def _leader(self, topic: str, partition: int) -> Tuple[str, int]:
        with self._mu:
            addr = self._leaders.get((topic, partition))
        if addr is None:
            self.metadata([topic])
            with self._mu:
                addr = self._leaders.get((topic, partition))
        if addr is None:
            raise EngineError(f"kafka: no leader for {topic}/{partition}")
        return addr

    def _leader_rpc(self, topic: str, partition: int, api_key: int,
                    api_version: int, body: bytes, parse,
                    timeout: Optional[float] = None):
        """Route to the partition leader and parse the response. Recovers
        once from either failure class: a transport error drops the cached
        conn + leader; a retriable broker error (NOT_LEADER etc. after a
        leader migration — the old broker still answers, so no transport
        error fires) invalidates the leader cache so the retry re-resolves
        via fresh metadata."""
        for attempt in (0, 1):
            addr = self._leader(topic, partition)
            try:
                return parse(self._conn(addr).request(api_key, api_version,
                                                      body, timeout))
            except (OSError, KafkaTransportError):
                self._drop_conn(addr)
                with self._mu:
                    self._leaders.pop((topic, partition), None)
                if attempt:
                    raise
            except KafkaBrokerError as e:
                if e.code not in _RETRIABLE_ROUTING or attempt:
                    raise
                with self._mu:
                    self._leaders.pop((topic, partition), None)
        raise AssertionError("unreachable")

    # -------------------------------------------------------------- offsets
    def list_offset(self, topic: str, partition: int, ts: int = _LATEST) -> int:
        """ts -1 = latest (next offset to be written), -2 = earliest."""
        body = _i32(-1) + _array([
            _string(topic) + _array([_i32(partition) + _i64(ts)])])

        def parse(r: _Reader) -> int:
            for _ in range(r.i32()):
                r.string()
                for _ in range(r.i32()):
                    r.i32()  # partition id
                    _check(r.i16(), f"ListOffsets({topic}/{partition})")
                    r.i64()  # timestamp
                    return r.i64()
            raise EngineError("kafka: empty ListOffsets response")

        return self._leader_rpc(topic, partition, 2, 1, body, parse)

    def earliest_offset(self, topic: str, partition: int) -> int:
        return self.list_offset(topic, partition, _EARLIEST)

    def latest_offset(self, topic: str, partition: int) -> int:
        return self.list_offset(topic, partition, _LATEST)

    # -------------------------------------------------------------- produce
    def produce(self, topic: str, partition: int,
                messages: List[Tuple[Optional[bytes], bytes, int]],
                acks: int = 1, timeout_ms: int = 10_000) -> int:
        """Returns the base offset the broker assigned (-1 with acks=0)."""
        mset = encode_message_set(messages)
        body = (_i16(acks) + _i32(timeout_ms) + _array([
            _string(topic) + _array([_i32(partition) + _bytes(mset)])]))
        if acks == 0:
            # fire-and-forget: broker sends no response
            addr = self._leader(topic, partition)
            conn = self._conn(addr)
            with conn.lock:
                conn.corr += 1
                hdr = (_i16(0) + _i16(2) + _i32(conn.corr)
                       + _string(self.client_id))
                payload = hdr + body
                conn.sock.sendall(_i32(len(payload)) + payload)
            return -1
        def parse(r: _Reader) -> int:
            base = -1
            for _ in range(r.i32()):
                r.string()
                for _ in range(r.i32()):
                    r.i32()  # partition id
                    _check(r.i16(), f"Produce({topic}/{partition})")
                    base = r.i64()
                    r.i64()  # log_append_time
            r.i32()  # throttle_time_ms
            return base

        return self._leader_rpc(topic, partition, 0, 2, body, parse,
                                timeout=max(self.timeout,
                                            timeout_ms / 1000 + 1))

    # ---------------------------------------------------------------- fetch
    #: fetch auto-grow ceiling — one message larger than this is an error
    MAX_FETCH_BYTES = 64 * 1024 * 1024

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1_000_000, max_wait_ms: int = 500,
              min_bytes: int = 1
              ) -> Tuple[int, List[Tuple[int, Optional[bytes], bytes, int]]]:
        """-> (high_watermark, [(offset, key, value, timestamp_ms)]).

        Fetch v2 (pre-KIP-74) truncates the first message at max_bytes if
        it is larger — decoding then yields zero complete messages while
        the log has more (hw > offset). That would busy-poll the same
        offset forever, so the request is retried with doubled max_bytes
        up to MAX_FETCH_BYTES, then errors loudly."""
        while True:
            body = (_i32(-1) + _i32(max_wait_ms) + _i32(min_bytes) + _array([
                _string(topic) + _array([
                    _i32(partition) + _i64(offset) + _i32(max_bytes)])]))

            def parse(r: _Reader):
                r.i32()  # throttle_time_ms
                hw, raw = -1, b""
                for _ in range(r.i32()):
                    r.string()
                    for _ in range(r.i32()):
                        r.i32()  # partition id
                        _check(r.i16(), f"Fetch({topic}/{partition})")
                        hw = r.i64()
                        raw = r.bytes_() or b""
                return hw, raw

            hw, raw = self._leader_rpc(
                topic, partition, 1, 2, body, parse,
                timeout=self.timeout + max_wait_ms / 1000)
            msgs = decode_message_set(raw)
            if msgs or not raw or hw <= offset:
                return hw, msgs
            if max_bytes >= self.MAX_FETCH_BYTES:
                raise EngineError(
                    f"kafka: message at {topic}/{partition} offset {offset} "
                    f"exceeds MAX_FETCH_BYTES ({self.MAX_FETCH_BYTES})")
            max_bytes = min(max_bytes * 2, self.MAX_FETCH_BYTES)

    def close(self) -> None:
        with self._mu:
            conns = list(self._conns.values())
            self._conns.clear()
            self._leaders.clear()
        for c in conns:
            c.close()
