"""Kafka source & sink — analogue of the reference's kafka extension
(extensions/impl/kafka/source.go, sink.go), built on the bundled wire
client (io/kafka_wire.py) instead of kafka-go.

Divergence (documented, COMPONENTS.md row 53): no consumer-group protocol.
The reference's source uses a groupID for broker-side offset tracking; this
engine tracks offsets through its own checkpoint machinery instead — the
source is Rewindable (io/contract.py), so offsets ride the rule's
checkpoint barriers and recovery replays from the exact checkpointed
position (at-least-once, same guarantee the reference gets from committing
group offsets after processing). A groupID prop is accepted and ignored
with a warning.

Source props: brokers, partition (int, default all partitions), offset
("earliest" | "latest" | int, default earliest — matching kafka-go's
group-less default), maxBytes, pollInterval (ms between empty polls).
Sink props: brokers, topic, key (static message key), partition (int,
default round-robin), requiredACKs (-1/0/1), batchSize, format.
Both: saslAuthType ("none" | "plain" | "scram_sha_256" | "scram_sha_512"),
saslUserName, password — the reference's SASL prop names
(source.go:255-277); SCRAM-SHA-256/512 are implemented in the bundled
wire client (io/kafka_wire.py, RFC 5802 with server-signature
verification).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.infra import EngineError, logger
from .contract import Rewindable, Sink, Source
from .converters import get_converter
from .kafka_wire import KafkaClient


_SASL_KINDS = {"plain": "PLAIN", "scram_sha_256": "SCRAM-SHA-256",
               "scram_sha_512": "SCRAM-SHA-512"}


def _sasl_of(props: Dict[str, Any]):
    """(mech, user, password) from the reference's prop names
    (saslAuthType plain/scram_sha_256/scram_sha_512), or None."""
    kind = str(props.get("saslAuthType", "none") or "none").lower()
    if kind in ("", "none"):
        return None
    mech = _SASL_KINDS.get(kind)
    if mech is None:
        raise EngineError(
            f"kafka: unsupported saslAuthType {kind!r} "
            f"(want one of {sorted(_SASL_KINDS)})")
    return (mech, str(props.get("saslUserName") or ""),
            str(props.get("password") or props.get("saslPassword") or ""))


class KafkaSource(Source, Rewindable):
    def __init__(self) -> None:
        self.topic = ""
        self.brokers = ""
        self.partition: Optional[int] = None
        self.start = "earliest"
        self.max_bytes = 1_000_000
        self.poll_interval = 0.1
        self.sasl = None
        self._client: Optional[KafkaClient] = None
        self._offsets: Dict[int, int] = {}  # partition -> next fetch offset
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.topic = datasource or props.get("topic", "")
        self.brokers = props.get("brokers", "")
        if not self.topic:
            raise EngineError("kafka source requires a topic (datasource)")
        if not self.brokers:
            raise EngineError("kafka: brokers can not be empty")
        if props.get("groupID"):
            logger.warning(
                "kafka source: groupID %r ignored — offsets are engine-"
                "checkpointed (Rewindable), not group-committed",
                props["groupID"])
        p = props.get("partition")
        self.partition = int(p) if p is not None else None
        self.start = props.get("offset", "earliest")
        self.max_bytes = int(props.get("maxBytes", 1_000_000))
        self.poll_interval = float(props.get("pollInterval", 100)) / 1000.0
        self.sasl = _sasl_of(props)

    def _note_failure(self, fails: Dict[int, int], retry_at: Dict[int, float],
                      p: int, off: int, e: Exception) -> None:
        n = fails.get(p, 0) + 1
        fails[p] = n
        log = logger.error if n >= 3 else logger.warning
        log("kafka fetch %s/%d at offset %d (attempt %d): %s",
            self.topic, p, off, n, e)
        # jittered exponential deadline (utils/backoff.py): N consumers
        # of a recovering partition must not re-fetch on the same beat
        from ..utils.backoff import backoff_delay_s

        retry_at[p] = time.monotonic() + backoff_delay_s(
            n, base_s=1.0, cap_s=30.0)

    def _init_offsets(self, client: KafkaClient) -> None:
        parts = ([self.partition] if self.partition is not None
                 else client.partitions(self.topic))
        with self._mu:
            for p in parts:
                if p in self._offsets:
                    continue  # rewound before open — keep the checkpoint
                if self.start == "latest":
                    self._offsets[p] = client.latest_offset(self.topic, p)
                elif self.start == "earliest":
                    self._offsets[p] = client.earliest_offset(self.topic, p)
                else:
                    self._offsets[p] = int(self.start)

    def open(self, ingest) -> None:
        self._client = KafkaClient(self.brokers, sasl=self.sasl)
        self._init_offsets(self._client)

        def loop() -> None:
            from .kafka_wire import OFFSET_OUT_OF_RANGE, KafkaBrokerError

            client = self._client
            # Failure policy, per partition so one sick partition never
            # stalls the healthy ones:
            #  - OFFSET_OUT_OF_RANGE: the checkpointed offset fell off the
            #    log (retention truncation while the rule was down). It can
            #    never succeed — clamp to earliest with a LOUD data-loss
            #    error (the reference's auto.offset.reset behavior).
            #  - anything else (poison batch, leader down): exponential
            #    backoff 1s..30s tracked as a per-partition deadline; other
            #    partitions keep polling at full rate.
            fails: Dict[int, int] = {}
            retry_at: Dict[int, float] = {}
            while not self._stop.is_set():
                got_any = False
                with self._mu:
                    positions = dict(self._offsets)
                now = time.monotonic()
                for p, off in positions.items():
                    if self._stop.is_set():
                        break
                    if retry_at.get(p, 0.0) > now:
                        continue
                    try:
                        _, msgs = client.fetch(
                            self.topic, p, off, max_bytes=self.max_bytes,
                            max_wait_ms=int(self.poll_interval * 1000))
                        fails.pop(p, None)
                        retry_at.pop(p, None)
                    except KafkaBrokerError as e:
                        if e.code == OFFSET_OUT_OF_RANGE:
                            earliest = client.earliest_offset(self.topic, p)
                            logger.error(
                                "kafka %s/%d: checkpointed offset %d is out "
                                "of range (log truncated by retention?) — "
                                "resetting to earliest %d; records in "
                                "between are LOST", self.topic, p, off,
                                earliest)
                            with self._mu:
                                if self._offsets.get(p) == off:
                                    self._offsets[p] = earliest
                            continue
                        self._note_failure(fails, retry_at, p, off, e)
                        continue
                    except Exception as e:
                        self._note_failure(fails, retry_at, p, off, e)
                        continue
                    for moff, key, value, ts in msgs:
                        if value is None:
                            # delete tombstone (null value, distinct from
                            # an empty payload): nothing to decode — skip
                            # the record but still advance past its offset.
                            # Progress was made: without got_any a run of
                            # tombstones (compacted topics) would throttle
                            # catch-up to one fetch per poll_interval
                            got_any = True
                            continue
                        ingest(value, {
                            "topic": self.topic, "partition": p,
                            "offset": moff, "timestamp": ts,
                            "key": key.decode(errors="replace") if key else None,
                        })
                        got_any = True
                    if msgs:
                        with self._mu:
                            # a rewind() that raced this batch wins — don't
                            # advance past it (recovery must replay; extra
                            # duplicates are fine under at-least-once)
                            if self._offsets.get(p) == off:
                                self._offsets[p] = msgs[-1][0] + 1
                if not got_any:
                    self._stop.wait(self.poll_interval)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"kafka-src-{self.topic}")
        self._thread.start()

    # Rewindable: offsets ride the rule checkpoint (nodes_source.py:284)
    def get_offset(self) -> Any:
        with self._mu:
            return {str(p): o for p, o in self._offsets.items()}

    def rewind(self, offset: Any) -> None:
        if not isinstance(offset, dict):
            return
        with self._mu:
            for p, o in offset.items():
                self._offsets[int(p)] = int(o)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=3)
        if self._client is not None:
            self._client.close()
            self._client = None


class KafkaSink(Sink):
    def __init__(self) -> None:
        self.topic = ""
        self.brokers = ""
        self.key: Optional[str] = None
        self.partition: Optional[int] = None
        self.sasl = None
        self.acks = 1
        self.format = "json"
        self._client: Optional[KafkaClient] = None
        self._parts: List[int] = []
        self._rr = 0

    def configure(self, props: Dict[str, Any]) -> None:
        self.topic = props.get("topic", "")
        self.brokers = props.get("brokers", "")
        if not self.topic:
            raise EngineError("kafka sink requires topic")
        if not self.brokers:
            raise EngineError("kafka: brokers can not be empty")
        self.key = props.get("key") or None
        p = props.get("partition")
        self.partition = int(p) if p is not None else None
        self.acks = int(props.get("requiredACKs", 1))
        self.format = props.get("format", "json")
        self.sasl = _sasl_of(props)

    def connect(self) -> None:
        self._client = KafkaClient(self.brokers, sasl=self.sasl)
        self._parts = ([self.partition] if self.partition is not None
                       else self._client.partitions(self.topic))

    def collect(self, item: Any) -> None:
        if self._client is None:
            self.connect()
        conv = get_converter(self.format)
        rows = item if isinstance(item, list) else [item]
        now = int(time.time() * 1000)
        key = self.key.encode() if self.key else None
        msgs = []
        for row in rows:
            payload = row if isinstance(row, (bytes, bytearray)) \
                else conv.encode(row)
            if isinstance(payload, str):
                payload = payload.encode()
            msgs.append((key, bytes(payload), now))
        part = self._parts[self._rr % len(self._parts)]
        self._rr += 1
        self._client.produce(self.topic, part, msgs, acks=self.acks)

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None
