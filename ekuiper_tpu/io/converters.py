"""Message converters (codecs) — analogue of eKuiper's internal/converter:
json, binary, delimited, urlencoded built-in; custom/protobuf via the schema
registry (converter.go:34-43). Symmetric encode/decode used by source decode
and sink encode stages.
"""
from __future__ import annotations

import base64
import json
import urllib.parse
from typing import Any, Callable, Dict, List, Optional, Union

from ..utils.infra import EngineError


class Converter:
    """message.Converter analogue (pkg/message/artifacts.go:37)."""

    def decode(self, payload: bytes) -> Union[Dict[str, Any], List[Dict[str, Any]]]:
        raise NotImplementedError

    def encode(self, message: Any) -> bytes:
        raise NotImplementedError


class JsonConverter(Converter):
    def decode(self, payload: bytes):
        out = json.loads(payload)
        if not isinstance(out, (dict, list)):
            raise EngineError(f"json payload must be object or array, got {type(out).__name__}")
        return out

    def encode(self, message: Any) -> bytes:
        return json.dumps(message, default=str).encode()


class BinaryConverter(Converter):
    """Raw bytes in a single `self` field (reference binary format)."""

    def decode(self, payload: bytes):
        return {"self": payload}

    def encode(self, message: Any) -> bytes:
        if isinstance(message, dict) and "self" in message:
            v = message["self"]
            return v if isinstance(v, bytes) else str(v).encode()
        if isinstance(message, bytes):
            return message
        raise EngineError("binary encode requires a 'self' field")


class DelimitedConverter(Converter):
    """CSV-style with configurable delimiter; needs field names from schema
    or a header line."""

    def __init__(self, delimiter: str = ",", fields: Optional[List[str]] = None) -> None:
        self.delimiter = delimiter or ","
        self.fields = fields

    def decode(self, payload: bytes):
        text = payload.decode().strip("\r\n")
        parts = text.split(self.delimiter)
        names = self.fields or [f"col{i}" for i in range(len(parts))]
        out: Dict[str, Any] = {}
        for name, raw in zip(names, parts):
            out[name] = _sniff(raw)
        return out

    def encode(self, message: Any) -> bytes:
        if isinstance(message, dict):
            names = self.fields or list(message.keys())
            return self.delimiter.join(
                "" if message.get(n) is None else str(message.get(n)) for n in names
            ).encode()
        if isinstance(message, list):
            return b"\n".join(self.encode(m) for m in message)
        raise EngineError("delimited encode requires dict or list")


class UrlEncodedConverter(Converter):
    def decode(self, payload: bytes):
        parsed = urllib.parse.parse_qs(payload.decode(), keep_blank_values=True)
        return {k: _sniff(v[0]) if len(v) == 1 else v for k, v in parsed.items()}

    def encode(self, message: Any) -> bytes:
        if not isinstance(message, dict):
            raise EngineError("urlencoded encode requires dict")
        return urllib.parse.urlencode(message).encode()


def _sniff(raw: str) -> Any:
    """Best-effort typed parse for text formats."""
    if raw == "":
        return ""
    low = raw.lower()
    if low == "true":
        return True
    if low == "false":
        return False
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    return raw


_registry: Dict[str, Callable[..., Converter]] = {
    "json": lambda **kw: JsonConverter(),
    "binary": lambda **kw: BinaryConverter(),
    "delimited": lambda **kw: DelimitedConverter(
        delimiter=kw.get("delimiter", ","), fields=kw.get("fields")
    ),
    "urlencoded": lambda **kw: UrlEncodedConverter(),
}


def register_converter(name: str, factory: Callable[..., Converter]) -> None:
    """modules.RegisterConverter analogue — protobuf/custom converters from
    the schema registry plug in here."""
    _registry[name.lower()] = factory


def get_converter(fmt: str, **kwargs) -> Converter:
    factory = _registry.get((fmt or "json").lower())
    if factory is None and (fmt or "").lower() == "protobuf":
        from . import protobuf_conv  # noqa: F401 — registers on import

        factory = _registry.get("protobuf")
    if factory is None:
        raise EngineError(f"unknown format {fmt!r}")
    return factory(**kwargs)
