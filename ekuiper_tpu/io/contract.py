"""IO contract — analogue of eKuiper's contract/api source/sink interfaces
(contract/api/source.go:24-70, sink.go:21-41).

Sources push decoded payloads (dict / list / Tuple) into an ingest callback;
sinks collect result rows. Both get (props, …) configuration at build time
from the registry (io/registry.py) mirroring the binder io factories.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

IngestFn = Callable[..., None]


class Source:
    """Push source (analogue api.Source / api.TupleSource)."""

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        pass

    def open(self, ingest: IngestFn) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LookupSource:
    """Lookup-table source (analogue api.LookupSource)."""

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        pass

    def open(self) -> None:
        pass

    def lookup(self, fields: List[str], keys: List[str], values: List[Any]) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class Sink:
    """Collector sink (analogue api.Sink / api.TupleCollector)."""

    def configure(self, props: Dict[str, Any]) -> None:
        pass

    def connect(self) -> None:
        pass

    def collect(self, item: Any) -> None:
        """item: dict (single) or list of dicts."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class Rewindable:
    """Sources that can report/replay offsets (contract/api/source.go:38-43)."""

    def get_offset(self) -> Any:
        raise NotImplementedError

    def rewind(self, offset: Any) -> None:
        raise NotImplementedError
