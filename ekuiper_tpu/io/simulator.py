"""Simulator source — analogue of internal/io/simulator: replays canned
payloads at a configured interval (or as fast as possible with interval=0),
optionally looping. The load generator for benches and trials.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..utils import timex
from .contract import Source


class SimulatorSource(Source):
    def __init__(self) -> None:
        self.data: List[Dict[str, Any]] = []
        self.interval_ms = 1000
        self.loop = True
        self.batch_size = 1
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.data = props.get("data", [])
        self.interval_ms = int(props.get("interval", 1000))
        self.loop = bool(props.get("loop", True))
        self.batch_size = int(props.get("batch_size", 1))

    def open(self, ingest) -> None:
        self._stop.clear()

        def run() -> None:
            idx = 0
            while not self._stop.is_set() and self.data:
                batch = []
                for _ in range(self.batch_size):
                    if idx >= len(self.data):
                        if not self.loop:
                            break
                        idx = 0
                    batch.append(self.data[idx])
                    idx += 1
                if not batch:
                    break
                ingest(batch if len(batch) > 1 else batch[0])
                if idx >= len(self.data) and not self.loop:
                    break
                if self.interval_ms > 0:
                    timex.sleep(self.interval_ms)

        self._thread = threading.Thread(target=run, daemon=True, name="simulator")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
