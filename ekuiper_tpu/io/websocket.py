"""Websocket source/sink on a shared data server (analogue of the
reference's internal/io/websocket + the shared httpserver data server,
internal/io/http/httpserver/data_server.go:36-103).

Server mode (no `addr` prop): endpoints ride ONE process-wide websocket
server per port — N rules on the same path share the listener, sources
receive every frame a connected client sends to their path, sinks broadcast
to every client connected to their path (the reference's
endpoint-refcounted data server semantics).

Client mode (`addr` prop, e.g. ws://host:port/path): the source dials out
and ingests received frames; the sink dials out and sends.

Built on the `websockets` sync API — one thread per connection, matching
the engine's thread-per-node fabric.
"""
from __future__ import annotations

import json
import threading
from typing import Any, Callable, Dict, List, Optional, Set

from ..utils.infra import logger
from .contract import Sink, Source


class _WsEndpoint:
    def __init__(self) -> None:
        self.sources: List[Callable[[Any], None]] = []
        self.clients: Set[Any] = set()
        self.lock = threading.Lock()
        self.refs = 0  # registered sources+sinks; 0 -> endpoint removed


class WsDataServer:
    """One websocket listener per port, shared by every endpoint
    (refcounted; closes when the last endpoint detaches)."""

    _servers: Dict[int, "WsDataServer"] = {}
    _glock = threading.Lock()

    def __init__(self, port: int) -> None:
        from websockets.sync.server import serve

        self.port = port
        self.endpoints: Dict[str, _WsEndpoint] = {}
        self.refs = 0
        self._lock = threading.Lock()
        self._server = serve(self._handler, "0.0.0.0", port)
        self.actual_port = self._server.socket.getsockname()[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"ws-data-server-{port}")
        self._thread.start()

    @classmethod
    def acquire(cls, port: int) -> "WsDataServer":
        with cls._glock:
            srv = cls._servers.get(port)
            if srv is None:
                srv = WsDataServer(port)
                cls._servers[port] = srv
            srv.refs += 1
            return srv

    def release(self) -> None:
        with WsDataServer._glock:
            self.refs -= 1
            if self.refs <= 0:
                WsDataServer._servers.pop(self.port, None)
                self._server.shutdown()

    def endpoint(self, path: str,
                 create: bool = False) -> Optional[_WsEndpoint]:
        """Registered endpoints only: connections to unknown paths are
        refused, and an endpoint disappears with its last source/sink —
        arbitrary client paths must not grow state on an open listener."""
        with self._lock:
            ep = self.endpoints.get(path)
            if ep is None and create:
                ep = _WsEndpoint()
                self.endpoints[path] = ep
            return ep

    def acquire_path(self, path: str) -> _WsEndpoint:
        ep = self.endpoint(path, create=True)
        with ep.lock:
            ep.refs += 1
        return ep

    def release_path(self, path: str) -> None:
        with self._lock:
            ep = self.endpoints.get(path)
            if ep is None:
                return
            with ep.lock:
                ep.refs -= 1
                if ep.refs <= 0:
                    del self.endpoints[path]

    # -------------------------------------------------------------- handling
    def _handler(self, conn) -> None:
        path = conn.request.path
        ep = self.endpoint(path)
        if ep is None:
            conn.close(code=1008, reason="unknown endpoint")
            return
        with ep.lock:
            ep.clients.add(conn)
        try:
            for msg in conn:
                payload = self._decode(msg)
                with ep.lock:
                    sources = list(ep.sources)
                for ingest in sources:
                    try:
                        ingest(payload)
                    except Exception as exc:
                        logger.warning("ws ingest error: %s", exc)
        except Exception:
            pass
        finally:
            with ep.lock:
                ep.clients.discard(conn)

    @staticmethod
    def _decode(msg: Any) -> Any:
        if isinstance(msg, (bytes, bytearray)):
            msg = msg.decode("utf-8", errors="replace")
        try:
            return json.loads(msg)
        except (ValueError, TypeError):
            return {"data": msg}

    def broadcast(self, path: str, data: str) -> int:
        ep = self.endpoint(path)
        if ep is None:
            return 0
        with ep.lock:
            clients = list(ep.clients)
        n = 0
        for c in clients:
            try:
                c.send(data)
                n += 1
            except Exception:
                with ep.lock:
                    ep.clients.discard(c)
        return n


class WebsocketSource(Source):
    def __init__(self) -> None:
        self.path = "/"
        self.addr = ""
        self.port = 10081
        self._server: Optional[WsDataServer] = None
        self._ingest: Optional[Callable] = None
        self._client = None
        self._stop = threading.Event()

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.path = datasource or props.get("path", "/")
        if not self.path.startswith("/"):
            self.path = "/" + self.path
        self.addr = props.get("addr", "")
        self.port = int(props.get("port", 10081))

    def open(self, ingest) -> None:
        self._ingest = ingest
        if self.addr:
            self._stop.clear()
            t = threading.Thread(target=self._client_loop, daemon=True,
                                 name=f"ws-src-{self.addr}")
            t.start()
            return
        self._server = WsDataServer.acquire(self.port)
        ep = self._server.acquire_path(self.path)
        with ep.lock:
            ep.sources.append(ingest)

    def _client_loop(self) -> None:
        from websockets.sync.client import connect

        from ..utils.backoff import Backoff

        bo = Backoff(base_s=0.5, cap_s=30.0)
        while not self._stop.is_set():
            try:
                with connect(self.addr, open_timeout=5) as ws:
                    if self._stop.is_set():
                        return  # stopped while dialing
                    self._client = ws
                    bo.reset()
                    while not self._stop.is_set():
                        # bounded recv so a silent peer can't pin the thread
                        # past close()
                        try:
                            msg = ws.recv(timeout=1.0)
                        except TimeoutError:
                            continue
                        self._ingest(WsDataServer._decode(msg))
            except Exception as exc:
                if self._stop.is_set():
                    return
                logger.warning("ws source reconnect (%s): %s", self.addr, exc)
                if bo.wait(self._stop):
                    return

    def close(self) -> None:
        self._stop.set()
        if self._client is not None:
            try:
                self._client.close()
            except Exception:
                pass
        if self._server is not None:
            ep = self._server.endpoint(self.path)
            if ep is not None:
                with ep.lock:
                    if self._ingest in ep.sources:
                        ep.sources.remove(self._ingest)
            self._server.release_path(self.path)
            self._server.release()
            self._server = None


class WebsocketSink(Sink):
    def __init__(self) -> None:
        self.path = "/"
        self.addr = ""
        self.port = 10081
        self._server: Optional[WsDataServer] = None
        self._client = None
        self._lock = threading.Lock()

    def configure(self, props: Dict[str, Any]) -> None:
        self.path = props.get("path", props.get("datasource", "/"))
        if not self.path.startswith("/"):
            self.path = "/" + self.path
        self.addr = props.get("addr", "")
        self.port = int(props.get("port", 10081))

    def connect(self) -> None:
        if self.addr:
            from websockets.sync.client import connect

            self._client = connect(self.addr)
        else:
            self._server = WsDataServer.acquire(self.port)
            self._server.acquire_path(self.path)

    def collect(self, item: Any) -> None:
        if isinstance(item, (str, bytes, bytearray)):
            data = item  # pre-encoded frames pass through verbatim
        else:
            data = json.dumps(item)
        if self._client is not None:
            with self._lock:
                self._client.send(data)
        elif self._server is not None:
            self._server.broadcast(self.path, data)

    def close(self) -> None:
        if self._client is not None:
            try:
                self._client.close()
            except Exception:
                pass
            self._client = None
        if self._server is not None:
            self._server.release_path(self.path)
            self._server.release()
            self._server = None
