"""Protobuf FORMAT converter — analogue of internal/converter/protobuf.

Streams declare FORMAT="protobuf", SCHEMAID="schemaName.MessageName"; the
schema registry supplies the compiled message class (schema/registry.go via
converter.go:34-43).
"""
from __future__ import annotations

from typing import Any

from ..utils.infra import EngineError
from .converters import Converter, register_converter


class ProtobufConverter(Converter):
    def __init__(self, schema_id: str = "", **_kw) -> None:
        if "." not in (schema_id or ""):
            raise EngineError(
                'protobuf format needs SCHEMAID="schema.Message"')
        schema_name, message_name = schema_id.split(".", 1)
        from ..schema.registry import SchemaRegistry

        self._cls = SchemaRegistry.global_instance().message_class(
            schema_name, message_name)

    def decode(self, raw: bytes) -> Any:
        from google.protobuf.json_format import MessageToDict

        msg = self._cls()
        msg.ParseFromString(bytes(raw))
        return MessageToDict(msg, preserving_proto_field_name=True)

    def encode(self, data: Any) -> bytes:
        from google.protobuf.json_format import ParseDict

        if isinstance(data, list):
            # protobuf is record-oriented: encode a single row per message
            if len(data) != 1:
                raise EngineError(
                    "protobuf encode expects one row (use sendSingle)")
            data = data[0]
        msg = ParseDict(data, self._cls(), ignore_unknown_fields=True)
        return msg.SerializeToString()


register_converter("protobuf", ProtobufConverter)
