"""ZeroMQ source & sink — analogue of the reference's zmq extension
(extensions/impl/zmq/{source,sink,conf}.go) over the bundled ZMTP 3.0
peer (io/zmq_native.py) instead of pebbe/zmq4 + libzmq.

Reference semantics preserved:
- sink = PUB that BINDS `server`; with a `topic` prop it sends
  [topic, payload] multipart, else a single payload frame (sink.go:66-80)
- source = SUB that CONNECTS and prefix-subscribes its datasource topic;
  multipart payload frames are concatenated and the topic frame is
  reported as meta (source.go:72-105)
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from ..utils.infra import EngineError
from .contract import Sink, Source
from .converters import get_converter
from .zmq_native import PubServer, SubClient


class ZmqSource(Source):
    def __init__(self) -> None:
        self.topic = ""
        self.server = ""
        self._client: Optional[SubClient] = None

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.topic = datasource or props.get("topic", "")
        self.server = props.get("server", "")
        if not self.server:
            raise EngineError("zmq source: missing server address")

    def open(self, ingest) -> None:
        topic = self.topic

        def on_message(parts) -> None:
            if not parts:
                return
            if topic and len(parts) >= 2:
                meta = {"topic": parts[0].decode(errors="replace")}
                payload = b"".join(parts[1:])
            else:
                # single-frame publishers embed the topic prefix in the
                # payload frame (canonical libzmq pattern) — deliver the
                # frame whole rather than mistaking it for a bare topic
                meta = {}
                payload = b"".join(parts)
            ingest(payload, meta)

        self._client = SubClient(self.server, topic, on_message)

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None


class ZmqSink(Sink):
    def __init__(self) -> None:
        self.server = ""
        self.topic = ""
        self.format = "json"
        self._pub: Optional[PubServer] = None

    def configure(self, props: Dict[str, Any]) -> None:
        self.server = props.get("server", "")
        self.topic = props.get("topic", "")
        self.format = props.get("format", "json")
        if not self.server:
            raise EngineError("zmq sink: missing server address")

    def connect(self) -> None:
        self._pub = PubServer(self.server)

    def collect(self, item: Any) -> None:
        if self._pub is None:
            self.connect()
        conv = get_converter(self.format)
        payload = item if isinstance(item, (bytes, bytearray)) \
            else conv.encode(item)
        if isinstance(payload, str):
            payload = payload.encode()
        if self.topic:
            self._pub.send([self.topic.encode(), bytes(payload)])
        else:
            self._pub.send([bytes(payload)])

    def close(self) -> None:
        if self._pub is not None:
            self._pub.close()
            self._pub = None
