"""Video frame source — analogue of the reference's video extension
(extensions/impl/video/source.go): pull one frame per interval from a
stream URL and ingest the raw image bytes (the decode pipeline or image
functions consume them downstream).

Transport divergence (documented): the reference shells out to ffmpeg
(mjpeg/image2 default) and so supports every ffmpeg input; this image has
no ffmpeg, so the bundled source speaks the two HTTP forms IP cameras
expose natively:

- **MJPEG over HTTP** (`multipart/x-mixed-replace` stream): a dedicated
  reader thread consumes the stream at camera rate into a one-slot latest
  buffer; each pull samples the NEWEST complete frame (true newest-wins —
  intermediate frames are dropped, the camera is never backpressured).
- **Snapshot endpoint** (any other content type): one GET per pull, body
  bytes are the frame (size-capped).

Props: url (required), interval (ms between pulls, default 1000,
minimum 10).
"""
from __future__ import annotations

import threading
import urllib.request
from typing import Any, Dict, Optional, Tuple

from ..utils.infra import EngineError, logger
from .contract import Source

_MAX_FRAME = 64 * 1024 * 1024


class _MjpegReader:
    """Continuously parses a multipart/x-mixed-replace stream on its own
    thread, keeping only the newest complete part."""

    def __init__(self, resp, boundary: bytes) -> None:
        self.resp = resp
        self.boundary = boundary
        self._buf = b""
        self._latest: Optional[bytes] = None
        self._mu = threading.Lock()
        self._have = threading.Event()
        self.dead = threading.Event()
        threading.Thread(target=self._run, daemon=True,
                         name="mjpeg-reader").start()

    def _next_part(self) -> Optional[bytes]:
        while True:
            start = self._buf.find(b"\r\n\r\n")
            if start != -1:
                nxt = self._buf.find(self.boundary, start + 4)
                if nxt != -1:
                    body = self._buf[start + 4:nxt]
                    self._buf = self._buf[nxt:]
                    body = body.rstrip(b"\r\n")
                    if body:
                        return body
                    continue
            chunk = self.resp.read(16384)
            if not chunk:
                return None
            self._buf += chunk
            if len(self._buf) > _MAX_FRAME:
                raise EngineError("video: mjpeg part exceeds 64MB")

    def _run(self) -> None:
        try:
            while True:
                part = self._next_part()
                if part is None:
                    break
                with self._mu:
                    self._latest = part
                self._have.set()
        except Exception:
            pass
        finally:
            self.dead.set()
            self._have.set()  # release any waiter

    def take_latest(self, timeout: float) -> Optional[bytes]:
        """Newest frame since the last take, or None."""
        self._have.wait(timeout)
        with self._mu:
            frame, self._latest = self._latest, None
            if frame is None:
                self._have.clear()
        return frame

    def close(self) -> None:
        try:
            self.resp.close()
        except OSError:
            pass


class VideoSource(Source):
    def __init__(self) -> None:
        self.url = ""
        self.interval = 1.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._reader: Optional[_MjpegReader] = None
        self._mu = threading.Lock()

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.url = props.get("url", "") or datasource
        if not self.url:
            raise EngineError("video source requires url")
        try:
            iv = float(props.get("interval", 1000))
        except (TypeError, ValueError):
            raise EngineError(
                f"video: interval must be numeric ms, got "
                f"{props.get('interval')!r}")
        # floor at 10ms — interval 0 would busy-hammer the endpoint
        self.interval = max(iv, 10.0) / 1000.0

    def _connect(self) -> Tuple[Optional["_MjpegReader"], Optional[bytes]]:
        """-> (mjpeg_reader, None) for streams, (None, body) for snapshots."""
        resp = urllib.request.urlopen(self.url, timeout=10)
        ctype = resp.headers.get("Content-Type", "")
        if "multipart/x-mixed-replace" in ctype:
            if "boundary=" not in ctype:
                raise EngineError("video: mjpeg stream without boundary")
            b = ctype.split("boundary=", 1)[1].strip().strip('"')
            if not b.startswith("--"):
                b = "--" + b
            return _MjpegReader(resp, b.encode()), None
        # snapshot endpoint: body IS the frame — cap the read so a
        # mislabeled endless stream can't hang/grow unboundedly
        body = resp.read(_MAX_FRAME + 1)
        resp.close()
        if len(body) > _MAX_FRAME:
            raise EngineError("video: snapshot exceeds 64MB "
                              "(mislabeled stream endpoint?)")
        return None, body

    def _set_reader(self, reader: Optional["_MjpegReader"]) -> bool:
        """Atomically install the reader; False (and reader closed) when
        close() already ran — the loop must exit without ingesting."""
        with self._mu:
            if self._stop.is_set():
                if reader is not None:
                    reader.close()
                return False
            self._reader = reader
            return True

    def open(self, ingest) -> None:
        def loop() -> None:
            seq = 0
            try:
                while not self._stop.is_set():
                    try:
                        frame = None
                        if self._reader is not None:
                            if self._reader.dead.is_set():
                                self._reader.close()
                                if not self._set_reader(None):
                                    return
                            else:
                                frame = self._reader.take_latest(
                                    self.interval)
                        if self._reader is None:
                            reader, snap = self._connect()
                            if not self._set_reader(reader):
                                return
                            frame = (reader.take_latest(10.0)
                                     if reader is not None else snap)
                        if self._stop.is_set():
                            return
                        if frame:
                            seq += 1
                            ingest(frame, {"url": self.url, "frame": seq})
                    except Exception as e:
                        if self._stop.is_set():
                            return
                        logger.warning("video source %s: %s", self.url, e)
                        if self._reader is not None:
                            self._reader.close()
                            if not self._set_reader(None):
                                return
                    self._stop.wait(self.interval)
            finally:
                with self._mu:
                    if self._reader is not None:
                        self._reader.close()
                        self._reader = None

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="video-src")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        with self._mu:
            if self._reader is not None:
                self._reader.close()
                self._reader = None
        if self._thread is not None:
            self._thread.join(timeout=3)
