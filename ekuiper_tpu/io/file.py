"""File source & sink — analogue of eKuiper's internal/io/file: streaming
reader for json/lines/csv files (optionally watching a directory), and a
rolling writer sink.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

from ..utils import timex
from ..utils.infra import EngineError, logger
from .contract import Sink, Source
from .converters import get_converter


class FileSource(Source):
    """Reads a file (or every file in a directory) and streams rows.

    props: fileType=json|lines|csv, path, interval (re-read period, 0=once),
    delimiter, sendInterval.
    """

    def __init__(self) -> None:
        self.path = ""
        self.file_type = "json"
        self.interval_ms = 0
        self.delimiter = ","
        self._offset = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.path = props.get("path", datasource)
        self.file_type = props.get("fileType", "json").lower()
        self.interval_ms = int(props.get("interval", 0))
        self.delimiter = props.get("delimiter", ",")

    def open(self, ingest) -> None:
        self._stop.clear()

        def run() -> None:
            while not self._stop.is_set():
                try:
                    skip = self._offset  # rewind/resume: replay from here
                    n = 0
                    for payload in self._read_all():
                        if self._stop.is_set():
                            return
                        n += 1
                        if n <= skip:
                            continue
                        ingest(payload, {"file": self.path})
                        self._offset = n
                except Exception as exc:
                    logger.error("file source %s: %s", self.path, exc)
                if self.interval_ms <= 0:
                    return
                self._offset = 0  # periodic re-reads restart the cycle
                timex.sleep(self.interval_ms)

        self._thread = threading.Thread(target=run, daemon=True, name="file-source")
        self._thread.start()

    # Rewindable (io/contract.py): offset = payloads emitted this cycle, so
    # a checkpoint-restored rule resumes a bounded file replay where it was
    def get_offset(self):
        return self._offset

    def rewind(self, offset) -> None:
        self._offset = int(offset or 0)

    def _files(self) -> List[str]:
        if os.path.isdir(self.path):
            return sorted(
                os.path.join(self.path, f) for f in os.listdir(self.path)
                if not f.startswith(".")
            )
        return [self.path]

    def _read_all(self):
        for fpath in self._files():
            if self.file_type == "json":
                with open(fpath, "rb") as f:
                    data = json.load(f)
                if isinstance(data, list):
                    yield data
                else:
                    yield data
            elif self.file_type == "lines":
                with open(fpath) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            yield json.loads(line)
            elif self.file_type == "csv":
                conv = get_converter("delimited", delimiter=self.delimiter)
                with open(fpath) as f:
                    header = f.readline().strip().split(self.delimiter)
                    conv.fields = header
                    for line in f:
                        line = line.strip()
                        if line:
                            yield conv.decode(line.encode())
            else:
                raise EngineError(f"unknown fileType {self.file_type}")

    def close(self) -> None:
        self._stop.set()


class FileSink(Sink):
    """Appends results to a file; rolling by size or interval
    (reference: rolling writer)."""

    def __init__(self) -> None:
        self.path = ""
        self.file_type = "lines"
        self.roll_size = 0  # bytes; 0 = no rolling
        self.roll_interval_ms = 0
        self._fh = None
        self._written = 0
        self._opened_at = 0
        self._lock = threading.Lock()
        self._roll_index = 0

    def configure(self, props: Dict[str, Any]) -> None:
        self.path = props.get("path", "sink_out.log")
        self.file_type = props.get("fileType", "lines").lower()
        self.roll_size = int(props.get("rollingSize", 0))
        self.roll_interval_ms = int(props.get("rollingInterval", 0))

    def connect(self) -> None:
        self._open_file()

    def _open_file(self) -> None:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(self.path, "ab")
        self._written = 0
        self._opened_at = timex.now_ms()

    def _maybe_roll(self) -> None:
        roll = False
        if self.roll_size and self._written >= self.roll_size:
            roll = True
        if (
            self.roll_interval_ms
            and timex.now_ms() - self._opened_at >= self.roll_interval_ms
            and self._written > 0
        ):
            roll = True
        if roll:
            self._fh.close()
            self._roll_index += 1
            rolled = f"{self.path}.{self._roll_index}"
            os.replace(self.path, rolled)
            self._open_file()

    def collect(self, item: Any) -> None:
        if isinstance(item, (bytes, bytearray)):
            line = bytes(item)  # opaque payload (compressed/encrypted)
        else:
            line = json.dumps(item, default=str).encode()
        with self._lock:
            if self._fh is None:
                self._open_file()
            self._fh.write(line + b"\n")
            self._fh.flush()
            self._written += len(line) + 1
            self._maybe_roll()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
