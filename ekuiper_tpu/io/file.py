"""File source & sink — analogue of eKuiper's internal/io/file: streaming
reader for json/lines/csv files (optionally watching a directory), and a
rolling writer sink.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

from ..utils import timex
from ..utils.infra import EngineError, logger
from .contract import Sink, Source
from .converters import get_converter


class FileSource(Source):
    """Reads a file (or every file in a directory) and streams rows.

    props: fileType=json|lines|csv, path, interval (re-read period, 0=once),
    delimiter, sendInterval.
    """

    def __init__(self) -> None:
        self.path = ""
        self.file_type = "json"
        self.interval_ms = 0
        self.delimiter = ","
        self._offset = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.path = props.get("path", datasource)
        self.file_type = props.get("fileType", "json").lower()
        self.interval_ms = int(props.get("interval", 0))
        self.delimiter = props.get("delimiter", ",")

    def open(self, ingest) -> None:
        self._stop.clear()

        def run() -> None:
            while not self._stop.is_set():
                try:
                    skip = self._offset  # rewind/resume: replay from here
                    n = 0
                    for payload in self._read_all():
                        if self._stop.is_set():
                            return
                        n += 1
                        if n <= skip:
                            continue
                        ingest(payload, {"file": self.path})
                        self._offset = n
                except Exception as exc:
                    logger.error("file source %s: %s", self.path, exc)
                if self.interval_ms <= 0:
                    return
                self._offset = 0  # periodic re-reads restart the cycle
                timex.sleep(self.interval_ms)

        self._thread = threading.Thread(target=run, daemon=True, name="file-source")
        self._thread.start()

    # Rewindable (io/contract.py): offset = payloads emitted this cycle, so
    # a checkpoint-restored rule resumes a bounded file replay where it was
    def get_offset(self):
        return self._offset

    def rewind(self, offset) -> None:
        self._offset = int(offset or 0)

    def _files(self) -> List[str]:
        if os.path.isdir(self.path):
            return sorted(
                os.path.join(self.path, f) for f in os.listdir(self.path)
                if not f.startswith(".")
            )
        return [self.path]

    def _read_all(self):
        for fpath in self._files():
            if self.file_type == "json":
                with open(fpath, "rb") as f:
                    data = json.load(f)
                yield data
            elif self.file_type == "lines":
                with open(fpath) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            yield json.loads(line)
            elif self.file_type == "csv":
                conv = get_converter("delimited", delimiter=self.delimiter)
                with open(fpath) as f:
                    header = f.readline().strip().split(self.delimiter)
                    conv.fields = header
                    for line in f:
                        line = line.strip()
                        if line:
                            yield conv.decode(line.encode())
            elif self.file_type == "parquet":
                yield from _read_parquet(fpath)
            else:
                raise EngineError(f"unknown fileType {self.file_type}")

    def close(self) -> None:
        self._stop.set()


def _pyarrow():
    try:
        import pyarrow  # noqa: F401
        import pyarrow.parquet as pq
    except ImportError as exc:  # pragma: no cover - pyarrow is in-image
        raise EngineError(
            "parquet fileType requires the pyarrow package") from exc
    return pq


def _read_parquet(fpath: str):
    """Stream a parquet file row-group by row-group (bounded memory), one
    list-of-dicts payload per group — the columnar analogue of the
    reference's parquet reader (internal/io/file, parquet build tag)."""
    pq = _pyarrow()
    pf = pq.ParquetFile(fpath)
    for i in range(pf.num_row_groups):
        rows = pf.read_row_group(i).to_pylist()
        if rows:
            yield rows


class FileSink(Sink):
    """Appends results to a file; rolling by size or interval
    (reference: rolling writer). fileType=parquet writes columnar row
    groups via pyarrow — the BatchWriterOp analogue: ColumnBatch
    emissions are written column-wise, never materialized as row dicts."""

    def __init__(self) -> None:
        self.path = ""
        self.file_type = "lines"
        self.roll_size = 0  # bytes; 0 = no rolling
        self.roll_interval_ms = 0
        self._fh = None
        self._written = 0
        self._opened_at = 0
        self._lock = threading.Lock()
        self._roll_index = 0
        self._pq_writer = None  # parquet: open ParquetWriter
        self.accepts_batches = False

    def configure(self, props: Dict[str, Any]) -> None:
        self.path = props.get("path", "sink_out.log")
        self.file_type = props.get("fileType", "lines").lower()
        self.roll_size = int(props.get("rollingSize", 0))
        self.roll_interval_ms = int(props.get("rollingInterval", 0))
        if self.file_type == "parquet":
            _pyarrow()  # fail at configure time when unavailable
            self.accepts_batches = True  # columnar fast path (nodes_sink)

    def connect(self) -> None:
        if self.file_type != "parquet":
            self._open_file()

    def _open_file(self) -> None:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(self.path, "ab")
        self._written = 0
        self._opened_at = timex.now_ms()

    def _maybe_roll(self) -> None:
        roll = False
        if self.roll_size and self._written >= self.roll_size:
            roll = True
        if (
            self.roll_interval_ms
            and timex.now_ms() - self._opened_at >= self.roll_interval_ms
            and self._written > 0
        ):
            roll = True
        if roll:
            self._fh.close()
            self._roll_index += 1
            rolled = f"{self.path}.{self._roll_index}"
            os.replace(self.path, rolled)
            self._open_file()

    def collect(self, item: Any) -> None:
        if self.file_type == "parquet":
            return self._collect_parquet(item)
        if isinstance(item, (bytes, bytearray)):
            line = bytes(item)  # opaque payload (compressed/encrypted)
        else:
            line = json.dumps(item, default=str).encode()
        with self._lock:
            if self._fh is None:
                self._open_file()
            self._fh.write(line + b"\n")
            self._fh.flush()
            self._written += len(line) + 1
            self._maybe_roll()

    # ----------------------------------------------------------- parquet
    def _to_arrow(self, item: Any):
        import pyarrow as pa

        from ..data.batch import ColumnBatch

        if isinstance(item, ColumnBatch):
            # columnar write: validity masks become arrow nulls, columns
            # never round-trip through per-row dicts
            arrays, names = [], []
            for name, col in item.columns.items():
                vm = item.valid.get(name)
                mask = None if vm is None else ~vm  # arrow: True = null
                if col.dtype == object:
                    arrays.append(pa.array(col.tolist(),
                                           mask=None if mask is None
                                           else mask))
                else:
                    arrays.append(pa.array(col, mask=mask))
                names.append(name)
            return pa.table(dict(zip(names, arrays)))
        rows = item if isinstance(item, list) else [item]
        rows = [r for r in rows if isinstance(r, dict)]
        if not rows:
            return None
        return pa.Table.from_pylist(rows)

    def _collect_parquet(self, item: Any) -> None:
        pq = _pyarrow()
        table = self._to_arrow(item)
        if table is None or table.num_rows == 0:
            return
        with self._lock:
            if self._pq_writer is None:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._pq_writer = pq.ParquetWriter(self.path, table.schema)
                self._written = 0
                self._opened_at = timex.now_ms()
            try:
                self._pq_writer.write_table(table)  # one row group
            except Exception:
                # schema drift across emissions: roll to a fresh file with
                # the new schema (parquet files are single-schema)
                self._roll_parquet()
                self._pq_writer = pq.ParquetWriter(self.path, table.schema)
                self._written = 0
                self._opened_at = timex.now_ms()
                self._pq_writer.write_table(table)
            self._written += table.nbytes
            roll = (self.roll_size and self._written >= self.roll_size) or (
                self.roll_interval_ms
                and timex.now_ms() - self._opened_at >= self.roll_interval_ms
                and self._written > 0)
            if roll:
                self._roll_parquet()

    def _roll_parquet(self) -> None:
        if self._pq_writer is not None:
            self._pq_writer.close()
            self._pq_writer = None
        if os.path.exists(self.path):
            self._roll_index += 1
            os.replace(self.path, f"{self.path}.{self._roll_index}")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            if self._pq_writer is not None:
                self._pq_writer.close()
                self._pq_writer = None
