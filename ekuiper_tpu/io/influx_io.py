"""InfluxDB sinks — line-protocol over plain HTTP, no client library.

Analogue of the reference's influx/influx2 extensions
(`extensions/impl/influx/influx.go:30-43` v1 conf {addr, username,
password, database, measurement} and `extensions/impl/influx2/
influx2.go:38-50` v2 conf {addr, token, org, bucket, precision,
measurement}, both sharing WriteOptions {precision, tags, tsFieldName}
from `extensions/impl/tspoint/transform.go:29-32`). The reference links
the vendor clients; the wire format is just line protocol over HTTP
POST, so this implementation speaks it directly:

    measurement,tag=v field1=1.5,field2="s",n=3i 1700000000000

v1 posts to /write?db=<database>&precision=<p> (basic auth), v2 to
/api/v2/write?org=<org>&bucket=<bucket>&precision=<p> (Token auth).
"""
from __future__ import annotations

import json
import re
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from ..utils import timex
from ..utils.infra import EngineError, logger
from .contract import Sink

_TMPL_RE = re.compile(r"{{\s*\.(\w+)\s*}}")


def _escape(s: str, *, quoted: bool = False) -> str:
    if quoted:  # string field value
        return s.replace("\\", "\\\\").replace('"', '\\"')
    # measurement/tag/field keys and tag values
    return (s.replace("\\", "\\\\").replace(",", "\\,")
            .replace("=", "\\=").replace(" ", "\\ "))


def _field_value(v: Any) -> Optional[str]:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, int):
        return f"{v}i"
    if isinstance(v, float):
        return json.dumps(v)
    if isinstance(v, str):
        return f'"{_escape(v, quoted=True)}"'
    return None  # arrays/objects are not line-protocol fields


def _render_tag(template: str, row: Dict[str, Any]) -> str:
    """Tags may be static strings or '{{.field}}' templates
    (tspoint WriteOptions.Tags)."""
    return _TMPL_RE.sub(lambda m: str(row.get(m.group(1), "")), template)


_MS_TO_PRECISION = {"ns": 1_000_000, "us": 1_000, "ms": 1, "s": 1 / 1000}


def to_lines(rows: List[Dict[str, Any]], measurement: str,
             tags: Dict[str, str], ts_field: str, precision: str) -> bytes:
    lines = []
    for row in rows:
        tag_parts = []
        for k, tmpl in tags.items():
            v = _render_tag(str(tmpl), row)
            if v:
                tag_parts.append(f"{_escape(k)}={_escape(v)}")
        # like the reference, ALL row fields (including tag-source ones)
        # stay fields; only the ts field is excluded
        # (tspoint/transform.go:112-117 Fields: mm)
        fields = []
        for k, v in row.items():
            if k == ts_field or v is None:
                continue
            fv = _field_value(v)
            if fv is not None:
                fields.append(f"{_escape(k)}={fv}")
        if not fields:
            continue
        line = _escape(measurement)
        if tag_parts:
            line += "," + ",".join(tag_parts)
        line += " " + ",".join(fields)
        if ts_field:
            ts = row.get(ts_field)
            if not isinstance(ts, (int, float)):
                continue  # ref errors the row; we drop it (counted upstream)
            # ref getTime: the field value is ALREADY in the precision unit
            line += f" {int(ts)}"
        else:
            # ref uses now() when no ts field is configured
            line += f" {int(timex.now_ms() * _MS_TO_PRECISION[precision])}"
        lines.append(line)
    return "\n".join(lines).encode()


class _BaseInfluxSink(Sink):
    def __init__(self) -> None:
        self.measurement = ""
        self.tags: Dict[str, str] = {}
        self.ts_field = ""
        self.precision = "ms"
        self._url = ""
        self._headers: Dict[str, str] = {}

    def _common(self, props: Dict[str, Any]) -> None:
        self.measurement = str(props.get("measurement", ""))
        if not self.measurement:
            raise EngineError("influx sink requires measurement")
        self.tags = dict(props.get("tags") or {})
        self.ts_field = str(props.get("tsFieldName", ""))
        self.precision = str(props.get("precision", "ms"))
        if self.precision not in _MS_TO_PRECISION:
            raise EngineError(f"bad precision {self.precision!r} "
                              "(want ns/us/ms/s)")

    def collect(self, item: Any) -> None:
        if isinstance(item, dict):
            rows = [item]
        elif isinstance(item, list):
            rows = [r for r in item if isinstance(r, dict)]
        else:
            try:  # columnar emissions flatten to rows
                rows = [t.message for t in item.to_tuples()]
            except AttributeError:
                raise EngineError(f"influx sink: invalid data {item!r}")
        body = to_lines(rows, self.measurement, self.tags, self.ts_field,
                        self.precision)
        if not body:
            return
        req = urllib.request.Request(self._url, data=body, method="POST",
                                     headers=self._headers)
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode(errors="replace")[:300]
            raise EngineError(
                f"influx write failed: {exc.code} {detail}") from exc


class InfluxSink(_BaseInfluxSink):
    """InfluxDB v1: POST /write?db=...&precision=... with basic auth."""

    def configure(self, props: Dict[str, Any]) -> None:
        self._common(props)
        addr = str(props.get("addr", "http://127.0.0.1:8086")).rstrip("/")
        database = str(props.get("database", ""))
        if not database:
            raise EngineError("influx sink requires database")
        q = urllib.parse.urlencode({"db": database,
                                    "precision": self.precision})
        self._url = f"{addr}/write?{q}"
        self._headers = {"Content-Type": "text/plain; charset=utf-8"}
        user = str(props.get("username", ""))
        if user:
            import base64

            cred = base64.b64encode(
                f"{user}:{props.get('password', '')}".encode()).decode()
            self._headers["Authorization"] = f"Basic {cred}"


class Influx2Sink(_BaseInfluxSink):
    """InfluxDB v2: POST /api/v2/write?org=...&bucket=... with Token auth."""

    def configure(self, props: Dict[str, Any]) -> None:
        self._common(props)
        addr = str(props.get("addr", "http://127.0.0.1:8086")).rstrip("/")
        org, bucket = str(props.get("org", "")), str(props.get("bucket", ""))
        if not (org and bucket):
            raise EngineError("influx2 sink requires org and bucket")
        q = urllib.parse.urlencode({"org": org, "bucket": bucket,
                                    "precision": self.precision})
        self._url = f"{addr}/api/v2/write?{q}"
        self._headers = {"Content-Type": "text/plain; charset=utf-8"}
        token = str(props.get("token", ""))
        if token:
            self._headers["Authorization"] = f"Token {token}"
