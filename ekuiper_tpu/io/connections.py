"""Connection management — named, reusable connector configs with
connectivity probing (analogue of the reference's connection CRUD + ping
routes, internal/server/rest.go connections handlers and
internal/pkg/connection registry).

A connection is {"id", "typ", "props"}; sources/sinks reference it through
a conf-key style profile, and `ping` checks reachability without starting a
rule."""
from __future__ import annotations

import json
import socket
from typing import Any, Dict, List
from urllib.parse import urlparse

from ..utils.infra import EngineError


class ConnectionManager:
    def __init__(self, store) -> None:
        self._kv = store.kv("connection")

    # ------------------------------------------------------------------ CRUD
    def create(self, spec: Dict[str, Any]) -> None:
        cid = spec.get("id", "")
        if not cid:
            raise EngineError("connection id is required")
        if not spec.get("typ"):
            raise EngineError("connection typ is required")
        _, exists = self._kv.get_ok(cid)
        if exists:
            raise EngineError(f"connection {cid} already exists")
        self._kv.set(cid, json.dumps(spec))

    def update(self, cid: str, spec: Dict[str, Any]) -> None:
        _, exists = self._kv.get_ok(cid)
        if not exists:
            raise EngineError(f"connection {cid} not found")
        self._kv.set(cid, json.dumps({**spec, "id": cid}))

    def get(self, cid: str) -> Dict[str, Any]:
        raw, ok = self._kv.get_ok(cid)
        if not ok:
            raise EngineError(f"connection {cid} not found")
        return json.loads(raw) if isinstance(raw, str) else raw

    def list(self) -> List[Dict[str, Any]]:
        return [self.get(k) for k in sorted(self._kv.keys())]

    def delete(self, cid: str) -> None:
        _, ok = self._kv.get_ok(cid)
        if not ok:
            raise EngineError(f"connection {cid} not found")
        self._kv.delete(cid)

    # ------------------------------------------------------------------ ping
    def ping(self, cid: str) -> str:
        spec = self.get(cid)
        return ping(spec.get("typ", ""), spec.get("props") or {})


def _tcp_probe(host: str, port: int, timeout: float = 3.0) -> None:
    with socket.create_connection((host, port), timeout=timeout):
        pass


def ping(typ: str, props: Dict[str, Any]) -> str:
    """Probe connectivity for a connector type; raises EngineError with the
    reason on failure, returns 'ok' on success."""
    typ = typ.lower()
    try:
        if typ in ("memory", "simulator", "file", "log", "nop"):
            return "ok"
        if typ in ("redis", "redissub"):
            from .redis_io import _client_from_props

            cli = _client_from_props(props)
            cli.connect()
            try:
                if cli.command("PING") not in ("PONG", b"PONG"):
                    raise EngineError("unexpected PING reply")
            finally:
                cli.close()
            return "ok"
        if typ == "websocket":
            addr = props.get("addr", "")
            if addr:
                from websockets.sync.client import connect

                connect(addr, open_timeout=3).close()
            return "ok"
        if typ in ("httppull", "httppush", "rest"):
            url = props.get("url", props.get("addr", ""))
            u = urlparse(url)
            if not u.hostname:
                raise EngineError(f"no url to probe in {props}")
            _tcp_probe(u.hostname, u.port or (443 if u.scheme == "https" else 80))
            return "ok"
        if typ == "mqtt":
            url = props.get("server", props.get("servers", ""))
            if isinstance(url, list):
                url = url[0] if url else ""
            u = urlparse(url if "://" in str(url) else f"tcp://{url}")
            _tcp_probe(u.hostname or "127.0.0.1", u.port or 1883)
            return "ok"
        if typ == "neuron":
            from ..plugin import ipc

            url = props.get("url", "neuron-ekuiper")
            s = ipc.Socket(ipc.PAIR)
            try:
                s.dial(url if "://" in url else ipc.ipc_url(url),
                       timeout_ms=3000)
            finally:
                s.close()
            return "ok"
        raise EngineError(f"ping not supported for connector type {typ!r}")
    except EngineError:
        raise
    except Exception as exc:
        raise EngineError(f"{typ} ping failed: {exc}")
