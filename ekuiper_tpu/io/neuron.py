"""Neuron source/sink — bidirectional pair channel to an industrial-gateway
process (analogue of internal/io/neuron over pkg/nng's PAIR socket,
sock.go:77-82).

Transport divergence, documented: the reference speaks the NNG pair wire
protocol on ipc:///tmp/neuron-ekuiper.ipc; this engine speaks its own
framed pair transport (native/ekipc.cpp, plugin/ipc.py) on a configurable
ipc:// url. Payload semantics match the reference: the source ingests
neuron's JSON tag messages ({"group_name","tag_name","values"/...}); the
sink writes {"group_name","tag_name","tag_value"} commands built from the
nodeName/groupName/tags props. A shared, refcounted connection serves all
neuron endpoints in the process (the reference shares one NNG socket the
same way).
"""
from __future__ import annotations

import json
import threading
from typing import Any, Callable, Dict, List, Optional

from ..utils.infra import EngineError, logger
from .contract import Sink, Source

DEFAULT_URL = "ipc://neuron-ekuiper"


class _NeuronConn:
    """One shared pair connection per url, refcounted across endpoints."""

    _conns: Dict[str, "_NeuronConn"] = {}
    _glock = threading.Lock()

    @staticmethod
    def normalize(url: str) -> str:
        from ..plugin import ipc

        return url if "://" in url else ipc.ipc_url(url)

    def __init__(self, url: str) -> None:
        from ..plugin import ipc

        self.url = url
        self.refs = 0
        self.sources: List[Callable[[Any], None]] = []
        self._lock = threading.Lock()
        # the pair socket is NOT safe for concurrent send/recv from
        # different threads (native transport); all socket IO serializes
        # through _io_lock, with short recv slices so sends never starve
        self._io_lock = threading.Lock()
        self._sock = ipc.Socket(ipc.PAIR)
        self._sock.dial(self.url, timeout_ms=5000)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._recv_loop, daemon=True, name=f"neuron-{url}")
        self._thread.start()

    @classmethod
    def acquire(cls, url: str) -> "_NeuronConn":
        url = cls.normalize(url)  # key and self.url must agree for release()
        with cls._glock:
            conn = cls._conns.get(url)
            if conn is None:
                conn = _NeuronConn(url)
                cls._conns[url] = conn
            conn.refs += 1
            return conn

    def release(self) -> None:
        with _NeuronConn._glock:
            self.refs -= 1
            if self.refs <= 0:
                _NeuronConn._conns.pop(self.url, None)
                self._stop.set()
                self._sock.close()

    def _recv_loop(self) -> None:
        while not self._stop.is_set():
            try:
                with self._io_lock:
                    raw = self._sock.recv(timeout_ms=50)
            except Exception:
                if self._stop.is_set():
                    return
                self._stop.wait(0.005)
                continue
            if raw is None:
                continue
            try:
                payload = json.loads(raw.decode("utf-8", errors="replace"))
            except ValueError:
                payload = {"data": raw.decode("utf-8", errors="replace")}
            with self._lock:
                sources = list(self.sources)
            for ingest in sources:
                try:
                    ingest(payload)
                except Exception as exc:
                    logger.warning("neuron ingest error: %s", exc)

    def add_source(self, ingest: Callable[[Any], None]) -> None:
        with self._lock:
            self.sources.append(ingest)

    def remove_source(self, ingest: Callable[[Any], None]) -> None:
        with self._lock:
            if ingest in self.sources:
                self.sources.remove(ingest)

    def send(self, data: bytes) -> None:
        with self._io_lock:
            self._sock.send(data, timeout_ms=5000)


class NeuronSource(Source):
    def __init__(self) -> None:
        self.url = DEFAULT_URL
        self._conn: Optional[_NeuronConn] = None
        self._ingest = None

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.url = props.get("url", datasource or DEFAULT_URL)

    def open(self, ingest) -> None:
        self._ingest = ingest
        self._conn = _NeuronConn.acquire(self.url)
        self._conn.add_source(ingest)

    def close(self) -> None:
        if self._conn is not None:
            self._conn.remove_source(self._ingest)
            self._conn.release()
            self._conn = None


class NeuronSink(Sink):
    """Writes tag commands: for each result row, one message per configured
    tag (raw=true passes rows through verbatim, reference neuron sink)."""

    def __init__(self) -> None:
        self.url = DEFAULT_URL
        self.node = ""
        self.group = ""
        self.tags: List[str] = []
        self.raw = False
        self._conn: Optional[_NeuronConn] = None

    def configure(self, props: Dict[str, Any]) -> None:
        self.url = props.get("url", DEFAULT_URL)
        self.node = props.get("nodeName", "")
        self.group = props.get("groupName", "")
        self.tags = props.get("tags") or []
        self.raw = bool(props.get("raw", False))
        if not self.raw and not (self.node and self.group):
            raise EngineError(
                "neuron sink requires nodeName and groupName (or raw=true)")

    def connect(self) -> None:
        self._conn = _NeuronConn.acquire(self.url)

    def collect(self, item: Any) -> None:
        rows = item if isinstance(item, list) else [item]
        for row in rows:
            if self.raw:
                data = row if isinstance(row, (bytes, bytearray)) else \
                    json.dumps(row).encode()
                self._conn.send(bytes(data))
                continue
            if not isinstance(row, dict):
                raise EngineError("neuron sink rows must be objects")
            tags = self.tags or list(row.keys())
            for tag in tags:
                if tag not in row:
                    continue
                self._conn.send(json.dumps({
                    "node_name": self.node,
                    "group_name": self.group,
                    "tag_name": tag,
                    "tag_value": row[tag],
                }).encode())

    def close(self) -> None:
        if self._conn is not None:
            self._conn.release()
            self._conn = None
