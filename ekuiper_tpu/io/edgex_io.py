"""EdgeX Foundry message-bus source & sink.

Analogue of the reference's edgex connector
(`internal/io/edgex/source.go:34-316`, `sink.go:35-392`): events ride the
EdgeX message bus as JSON `MessageEnvelope`s whose payload is an Event DTO
(or an AddEventRequest wrapper when messageType="request"); readings carry
their value as a STRING plus a `valueType` tag, and the source maps them
back to typed values (`source.go:203-280` getValue). The reference links
the official go-mod-messaging client; this image bundles no EdgeX client
library, so the bus rides the repo's OWN transport clients instead — the
native MQTT 3.1.1 client (io/mqtt_native.py) or the RESP redis client
(io/redis_io.py), the same two brokers EdgeX itself deploys on.

Envelope shape (go-mod-messaging types.MessageEnvelope, JSON-marshaled:
[]byte payload encodes as base64):

    {"apiVersion": "v3", "receivedTopic": ..., "correlationID": ...,
     "contentType": "application/json", "payload": "<base64>"}

A raw (non-enveloped) Event JSON payload is also accepted on the source
side — some EdgeX deployments publish bare events on MQTT.
"""
from __future__ import annotations

import base64
import json
import threading
import uuid
from typing import Any, Callable, Dict, List, Optional

from ..utils.infra import EngineError, logger
from .contract import Sink, Source

API_VERSION = "v3"

# EdgeX value types (go-mod-core-contracts v4/common/constants.go)
VT_BOOL = "Bool"
VT_STRING = "String"
VT_UINT8, VT_UINT16, VT_UINT32, VT_UINT64 = ("Uint8", "Uint16", "Uint32",
                                             "Uint64")
VT_INT8, VT_INT16, VT_INT32, VT_INT64 = "Int8", "Int16", "Int32", "Int64"
VT_FLOAT32, VT_FLOAT64 = "Float32", "Float64"
VT_BINARY = "Binary"
VT_OBJECT = "Object"

_INT_TYPES = {VT_INT8, VT_INT16, VT_INT32, VT_INT64,
              VT_UINT8, VT_UINT16, VT_UINT32}
_INT_ARRAY_TYPES = {t + "Array" for t in _INT_TYPES} | {"Uint64Array"}


def decode_reading_value(reading: Dict[str, Any]):
    """Typed value of one BaseReading (ref source.go:203-280 getValue).
    Raises ValueError on an unparsable value (caller logs + skips, like
    the reference's warn-and-continue)."""
    vt = reading.get("valueType", VT_STRING)
    v = reading.get("value", "")
    if vt == VT_BOOL:
        low = str(v).strip().lower()
        if low in ("true", "1"):
            return True
        if low in ("false", "0"):
            return False
        raise ValueError(f"bad bool {v!r}")
    if vt in _INT_TYPES or vt == VT_UINT64:
        return int(str(v), 10)
    if vt in (VT_FLOAT32, VT_FLOAT64):
        return float(v)
    if vt == VT_STRING:
        return v
    if vt == VT_BINARY:
        raw = reading.get("binaryValue", "")
        return base64.b64decode(raw) if isinstance(raw, str) else bytes(raw)
    if vt == VT_OBJECT:
        return reading.get("objectValue")
    if vt.endswith("Array"):
        val = json.loads(v) if isinstance(v, str) else v
        if not isinstance(val, list):
            raise ValueError(f"bad array {v!r}")
        if vt == "BoolArray":
            return [bool(x) for x in val]
        if vt in _INT_ARRAY_TYPES:
            return [int(x) for x in val]
        if vt in ("Float32Array", "Float64Array"):
            # ref convertFloatArray: accepts ["1.2", ...] or [1.2, ...]
            return [float(x) for x in val]
        if vt == "StringArray":
            return [str(x) for x in val]
    # ref: "Not supported type, processed as string value"
    logger.warning("edgex: unsupported valueType %s treated as string", vt)
    return v


def infer_value_type(v: Any):
    """(valueType, formatted) for a result value (ref sink.go:195-292
    getValueType — Python has no sized ints, so ints map to Int64 and
    floats to Float64, matching the reference's reflect.Int/Float64)."""
    if v is None:
        raise ValueError("unsupported value nil")
    if isinstance(v, bool):
        return VT_BOOL, "true" if v else "false"
    if isinstance(v, int):
        return VT_INT64, str(v)
    if isinstance(v, float):
        return VT_FLOAT64, json.dumps(v)
    if isinstance(v, str):
        return VT_STRING, v
    if isinstance(v, (bytes, bytearray)):
        return VT_BINARY, bytes(v)
    if isinstance(v, (list, tuple)):
        vals = list(v)
        if vals and all(isinstance(x, bool) for x in vals):
            return "BoolArray", json.dumps(vals)
        if vals and all(isinstance(x, int) and not isinstance(x, bool)
                        for x in vals):
            return "Int64Array", json.dumps(vals)
        if vals and all(isinstance(x, (int, float))
                        and not isinstance(x, bool) for x in vals):
            return "Float64Array", json.dumps([float(x) for x in vals])
        if all(isinstance(x, str) for x in vals):
            return "StringArray", json.dumps(vals)
        return VT_OBJECT, vals
    if isinstance(v, dict):
        return VT_OBJECT, v
    raise ValueError(f"unsupported value {v!r} ({type(v).__name__})")


# --------------------------------------------------------------- transports
class _Bus:
    """Minimal pub/sub transport facade over the in-repo clients."""

    def subscribe(self, topic: str, on_msg: Callable[[str, bytes], None]) -> None:
        raise NotImplementedError

    def publish(self, topic: str, payload: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class _MqttBus(_Bus):
    def __init__(self, props: Dict[str, Any]) -> None:
        from . import mqtt as mqtt_mod

        self._server = str(props.get("server",
                                     props.get("mqttServer",
                                               "tcp://127.0.0.1:1883")))
        self._client_id = str(props.get("clientid",
                                        f"ekuiper-edgex-{uuid.uuid4().hex[:8]}"))
        self._cli = mqtt_mod._acquire(
            self._server, self._client_id,
            str(props.get("username", "")), str(props.get("password", "")))
        self._mqtt_mod = mqtt_mod
        self._topics: List[str] = []

    def subscribe(self, topic: str, on_msg) -> None:
        def cb(_client, _userdata, msg):
            on_msg(msg.topic, bytes(msg.payload))

        self._cli.message_callback_add(topic, cb)
        self._cli.subscribe(topic)
        self._topics.append(topic)

    def publish(self, topic: str, payload: bytes) -> None:
        self._cli.publish(topic, payload)

    def close(self) -> None:
        # the pooled client may outlive this bus (shared clientid): drop
        # our callbacks + subscriptions so a closed source stops ingesting
        for topic in self._topics:
            try:
                self._cli.message_callback_remove(topic)
                self._cli.unsubscribe(topic)
            except Exception:
                pass
        self._topics = []
        self._mqtt_mod._release(self._server, self._client_id)


class _RedisBus(_Bus):
    """EdgeX redis message bus: topics are pub/sub channels; EdgeX maps
    topic separators '/' to '.' on redis (go-mod-messaging redis impl)."""

    def __init__(self, props: Dict[str, Any]) -> None:
        from .redis_io import _client_from_props

        self._props = dict(props)
        self._make = lambda: _client_from_props(self._props)
        self._pub = None
        self._sub_threads: List[threading.Thread] = []
        self._sub_clients: List[Any] = []
        self._stop = threading.Event()
        self._lock = threading.Lock()

    @staticmethod
    def _chan(topic: str) -> str:
        return topic.replace("/", ".").replace("#", "*").replace("+", "*")

    def subscribe(self, topic: str, on_msg) -> None:
        chan = self._chan(topic)
        pattern = "*" in chan

        def loop() -> None:
            from ..utils.backoff import Backoff

            bo = Backoff(base_s=0.5, cap_s=30.0)
            while not self._stop.is_set():
                cli = None
                try:
                    cli = self._make()
                    cli.connect()
                    cli._sock.settimeout(None)
                    with self._lock:
                        self._sub_clients.append(cli)
                    cli.send("PSUBSCRIBE" if pattern else "SUBSCRIBE", chan)
                    bo.reset()
                    while not self._stop.is_set():
                        reply = cli.read_reply()
                        if not isinstance(reply, list) or len(reply) < 3:
                            continue
                        kind = reply[0]
                        kind = kind.decode() if isinstance(kind, bytes) else kind
                        if kind == "message":
                            t, payload = reply[1], reply[2]
                        elif kind == "pmessage" and len(reply) >= 4:
                            t, payload = reply[2], reply[3]
                        else:
                            continue
                        t = t.decode() if isinstance(t, bytes) else str(t)
                        if isinstance(payload, str):
                            payload = payload.encode()
                        on_msg(t.replace(".", "/"), bytes(payload))
                except Exception as exc:
                    if cli is not None:  # close + forget the dead client
                        with self._lock:
                            if cli in self._sub_clients:
                                self._sub_clients.remove(cli)
                        try:
                            cli.close()
                        except Exception:
                            pass
                    if self._stop.is_set():
                        return
                    logger.warning("edgex redis bus reconnect: %s", exc)
                    if bo.wait(self._stop):
                        return

        th = threading.Thread(target=loop, daemon=True, name="edgex-redis-sub")
        th.start()
        self._sub_threads.append(th)

    def publish(self, topic: str, payload: bytes) -> None:
        with self._lock:
            if self._pub is None:
                self._pub = self._make()
                self._pub.connect()
            self._pub.command("PUBLISH", self._chan(topic), payload)

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            clients = list(self._sub_clients)
            self._sub_clients.clear()
            pub, self._pub = self._pub, None
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        if pub is not None:
            pub.close()


def _make_bus(props: Dict[str, Any]) -> _Bus:
    proto = str(props.get("protocol", props.get("type", "redis"))).lower()
    if proto in ("mqtt", "tcp"):
        return _MqttBus(props)
    if proto in ("redis", "redis-pubsub"):
        return _RedisBus(props)
    raise EngineError(f"edgex: unsupported message bus protocol {proto!r}")


# ------------------------------------------------------------------- source
class EdgexSource(Source):
    """Subscribe to an EdgeX bus topic and ingest one message per event:
    {resourceName: typed value} plus reading/event metadata (ref
    source.go:107-201 Subscribe)."""

    def __init__(self) -> None:
        self.topic = ""
        self.message_type = "event"
        self.props: Dict[str, Any] = {}
        self._bus: Optional[_Bus] = None

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.topic = (datasource or str(props.get("topic", ""))
                      or "rules-events")
        mt = str(props.get("messageType", "event"))
        if mt not in ("event", "request"):
            raise EngineError(f"edgex: bad messageType {mt!r}")
        self.message_type = mt
        self.props = props

    def open(self, ingest) -> None:
        self._bus = _make_bus(self.props)

        def on_msg(topic: str, payload: bytes) -> None:
            try:
                result, meta = self._decode(payload)
            except Exception as exc:
                logger.error("edgex source: bad payload on %s: %s", topic, exc)
                return
            if result:
                ingest(result, meta)
            else:
                logger.warning("edgex source: event with no readings ignored")

        self._bus.subscribe(self.topic, on_msg)

    def _decode(self, payload: bytes):
        doc = json.loads(payload)
        correlation = ""
        if isinstance(doc, dict) and "payload" in doc and "event" not in doc \
                and "readings" not in doc:
            # MessageEnvelope: payload is base64 of the event JSON
            correlation = str(doc.get("correlationID", ""))
            inner = doc.get("payload", "")
            raw = (base64.b64decode(inner) if isinstance(inner, str)
                   else bytes(inner))
            doc = json.loads(raw)
        event = doc.get("event", doc) if self.message_type == "request" \
            else (doc.get("event") or doc)
        readings = event.get("readings") or []
        result: Dict[str, Any] = {}
        meta: Dict[str, Any] = {}
        for r in readings:
            name = r.get("resourceName", "")
            if not name:
                logger.warning("edgex: reading without resourceName skipped")
                continue
            try:
                result[name] = decode_reading_value(r)
            except Exception as exc:
                logger.warning("edgex: fail to get value for %s: %s",
                               name, exc)
                continue
            rmeta = {"id": r.get("id"), "origin": r.get("origin"),
                     "deviceName": r.get("deviceName"),
                     "profileName": r.get("profileName"),
                     "valueType": r.get("valueType")}
            if r.get("mediaType"):
                rmeta["mediaType"] = r["mediaType"]
            meta[name] = rmeta
        if result:
            meta.update({
                "id": event.get("id"),
                "deviceName": event.get("deviceName"),
                "profileName": event.get("profileName"),
                "sourceName": event.get("sourceName"),
                "origin": event.get("origin"),
                "tags": event.get("tags"),
                "correlationid": correlation,
            })
        return result, meta

    def close(self) -> None:
        if self._bus is not None:
            self._bus.close()


# --------------------------------------------------------------------- sink
class EdgexSink(Sink):
    """Publish result rows as EdgeX events (ref sink.go EdgexMsgBusSink).
    One event per collect(): every row's fields become readings, with
    value types inferred from the Python values, or overridden per
    reading through the `metadata` field (ref getMeta/readingMeta)."""

    def __init__(self) -> None:
        self.props: Dict[str, Any] = {}
        self.topic = ""
        self.topic_prefix = ""
        self.message_type = "event"
        self.content_type = "application/json"
        self.device_name = "ekuiper"
        self.profile_name = "ekuiperProfile"
        self.source_name = ""
        self.metadata_field = ""
        self.fields: List[str] = []
        self.data_field = ""
        self._bus: Optional[_Bus] = None

    def configure(self, props: Dict[str, Any]) -> None:
        self.props = props
        self.topic = str(props.get("topic", ""))
        self.topic_prefix = str(props.get("topicPrefix", ""))
        if self.topic and self.topic_prefix:
            raise EngineError(
                "not allow to specify both topic and topicPrefix, "
                "please set one only")
        mt = str(props.get("messageType", "event"))
        if mt not in ("event", "request"):
            raise EngineError(f"specified wrong messageType value {mt}")
        self.message_type = mt
        self.content_type = str(props.get("contentType", "application/json"))
        if mt == "event" and self.content_type != "application/json":
            raise EngineError(
                f"specified wrong contentType value {self.content_type}: "
                "only 'application/json' is supported if messageType is "
                "event")
        self.device_name = str(props.get("deviceName", "ekuiper"))
        self.profile_name = str(props.get("profileName", "ekuiperProfile"))
        self.source_name = str(props.get("sourceName", ""))
        self.metadata_field = str(props.get("metadata", ""))
        self.fields = list(props.get("fields") or [])
        self.data_field = str(props.get("dataField", ""))

    def connect(self) -> None:
        self._bus = _make_bus(self.props)

    # -------------------------------------------------------------- events
    def _rows(self, item: Any) -> List[Dict[str, Any]]:
        if isinstance(item, dict):
            rows = [item]
        elif isinstance(item, list):
            rows = [r for r in item if isinstance(r, dict)]
        else:
            try:  # columnar emissions (ColumnBatch) flatten to rows
                rows = [t.message for t in item.to_tuples()]
            except AttributeError:
                raise EngineError(f"edgex sink: invalid data {item!r}")
        if self.data_field:
            out = []
            for r in rows:
                v = r.get(self.data_field)
                if isinstance(v, dict):
                    out.append(v)
                elif isinstance(v, list):
                    out.extend(x for x in v if isinstance(x, dict))
            rows = out
        if self.fields:
            rows = [{k: r[k] for k in self.fields if k in r} for r in rows]
        return rows

    def _event_meta(self, rows: List[Dict[str, Any]]):
        """Event-level + per-reading overrides from the metadata field
        (ref sink.go getMeta: the row's `metadata` entry may carry event
        fields and {reading: {...}} decorations)."""
        ev: Dict[str, Any] = {}
        readings_meta: Dict[str, Dict[str, Any]] = {}
        if self.metadata_field:
            for row in rows:
                md = row.get(self.metadata_field)
                if not isinstance(md, dict):
                    continue
                for k in ("id", "deviceName", "profileName", "sourceName",
                          "origin"):
                    if k in md and md[k] is not None:
                        ev.setdefault(k, md[k])
                for k, v in md.items():
                    if isinstance(v, dict):
                        readings_meta.setdefault(k, {}).update(v)
        return ev, readings_meta

    def produce_event(self, item: Any) -> Dict[str, Any]:
        from ..utils import timex

        rows = self._rows(item)
        ev_meta, readings_meta = self._event_meta(rows)
        origin = int(ev_meta.get("origin") or timex.now_ms() * 1_000_000)
        event = {
            "apiVersion": API_VERSION,
            "id": str(ev_meta.get("id") or uuid.uuid4()),
            "deviceName": str(ev_meta.get("deviceName") or self.device_name),
            "profileName": str(ev_meta.get("profileName")
                               or self.profile_name),
            "sourceName": str(ev_meta.get("sourceName") or self.source_name),
            "origin": origin,
            "readings": [],
        }
        for row in rows:
            for k, v in row.items():
                if k == self.metadata_field or v is None:
                    continue
                rmeta = readings_meta.get(k) or {}
                try:
                    if rmeta.get("valueType"):
                        vt = str(rmeta["valueType"])
                        _, formatted = infer_value_type(v)
                        if vt == VT_OBJECT:
                            formatted = v
                        elif vt == VT_BINARY and not isinstance(
                                formatted, (bytes, bytearray)):
                            formatted = str(formatted).encode()
                    else:
                        vt, formatted = infer_value_type(v)
                except (ValueError, TypeError) as exc:
                    # ref logs and continues on a bad reading (sink.go:181)
                    logger.error("edgex sink: %s", exc)
                    continue
                reading = {
                    "id": str(rmeta.get("id") or uuid.uuid4()),
                    "origin": int(rmeta.get("origin") or origin),
                    "deviceName": str(rmeta.get("deviceName")
                                      or event["deviceName"]),
                    "profileName": str(rmeta.get("profileName")
                                       or event["profileName"]),
                    "resourceName": k,
                    "valueType": vt,
                }
                if vt == VT_BINARY:
                    reading["binaryValue"] = base64.b64encode(
                        formatted).decode()
                    reading["mediaType"] = str(rmeta.get("mediaType")
                                               or "application/text")
                    reading["value"] = ""
                elif vt == VT_OBJECT:
                    reading["objectValue"] = formatted
                    reading["value"] = ""
                else:
                    reading["value"] = formatted
                event["readings"].append(reading)
        return event

    def _topic_for(self, event: Dict[str, Any]) -> str:
        if self.topic:
            return self.topic
        if self.topic_prefix:
            return "/".join([self.topic_prefix, event["profileName"],
                             event["deviceName"],
                             event["sourceName"] or "ekuiper"])
        return "application"

    def collect(self, item: Any) -> None:
        event = self.produce_event(item)
        if not event["readings"]:
            return
        if self.message_type == "request":
            payload = {"apiVersion": API_VERSION,
                       "requestId": str(uuid.uuid4()), "event": event}
        else:
            payload = event
        raw = json.dumps(payload, default=str).encode()
        envelope = {
            "apiVersion": API_VERSION,
            "correlationID": str(uuid.uuid4()),
            "contentType": self.content_type,
            "payload": base64.b64encode(raw).decode(),
        }
        self._bus.publish(self._topic_for(event),
                          json.dumps(envelope).encode())

    def close(self) -> None:
        if self._bus is not None:
            self._bus.close()
