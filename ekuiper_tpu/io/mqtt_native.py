"""Minimal native MQTT 3.1.1 client — no client library required.

MQTT is the reference's flagship protocol (its headline benchmarks are all
MQTT ingest), so it must work out of the box; paho is preferred when
installed (io/mqtt.py), and this module supplies a drop-in subset of paho's
Client API otherwise (io/registry.py picks whichever imports).

Implements the client side of MQTT 3.1.1 (OASIS spec):
CONNECT/CONNACK, SUBSCRIBE/SUBACK, UNSUBSCRIBE, PUBLISH qos0/qos1 (incoming
qos1 is PUBACK'd; outgoing qos1 is fire-and-track), PINGREQ keepalive,
DISCONNECT. TLS and qos2 are not implemented (the reference's benchmarks use
qos0/1 plaintext).
"""
from __future__ import annotations

import itertools
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.infra import logger

MQTT_ERR_SUCCESS = 0

CONNECT, CONNACK = 0x10, 0x20
PUBLISH, PUBACK = 0x30, 0x40
SUBSCRIBE, SUBACK = 0x82, 0x90
UNSUBSCRIBE, UNSUBACK = 0xA2, 0xB0
PINGREQ, PINGRESP = 0xC0, 0xD0
DISCONNECT = 0xE0


def encode_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        out.append(b | 0x80 if n else b)
        if not n:
            return bytes(out)


def encode_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def topic_matches(filt: str, topic: str) -> bool:
    """MQTT topic filter matching (+ single level, # multi level)."""
    fparts, tparts = filt.split("/"), topic.split("/")
    for i, fp in enumerate(fparts):
        if fp == "#":
            return True
        if i >= len(tparts):
            return False
        if fp != "+" and fp != tparts[i]:
            return False
    return len(fparts) == len(tparts)


class _Msg:
    __slots__ = ("topic", "payload", "qos", "mid")

    def __init__(self, topic: str, payload: bytes, qos: int, mid: int) -> None:
        self.topic = topic
        self.payload = payload
        self.qos = qos
        self.mid = mid


class _PublishInfo:
    def __init__(self, rc: int = MQTT_ERR_SUCCESS) -> None:
        self.rc = rc


class Client:
    """paho-shaped subset over a raw socket."""

    def __init__(self, client_id: str = "") -> None:
        self.client_id = client_id or f"ektpu-{int(time.time() * 1000) & 0xFFFFFF:x}"
        self._user = ""
        self._pass = ""
        self._sock: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        self._mids = itertools.count(1)
        self._callbacks: List[Tuple[str, Callable]] = []
        self._subs: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._connack = threading.Event()
        self._keepalive = 60
        self._host, self._port = "127.0.0.1", 1883
        self.on_message: Optional[Callable] = None

    # ------------------------------------------------------------- paho API
    def username_pw_set(self, username: str, password: str = "") -> None:
        self._user, self._pass = username, password or ""

    def connect(self, host: str, port: int = 1883, keepalive: int = 60) -> None:
        self._host, self._port, self._keepalive = host, port, keepalive
        self._dial()

    def _dial(self) -> None:
        self._sock = socket.create_connection((self._host, self._port),
                                              timeout=10)
        flags = 0x02  # clean session
        payload = encode_str(self.client_id)
        if self._user:
            flags |= 0x80
            payload += encode_str(self._user)
            if self._pass:
                flags |= 0x40
                payload += encode_str(self._pass)
        var = (encode_str("MQTT") + bytes([4, flags])
               + struct.pack(">H", self._keepalive))
        self._send_packet(CONNECT, var + payload)
        # CONNACK read inline (loop thread not started yet on first dial)
        typ, body = self._read_packet()
        if typ != CONNACK or len(body) < 2 or body[1] != 0:
            raise ConnectionError(f"mqtt connect refused: {body!r}")
        self._connack.set()

    def loop_start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mqtt-native")
        self._thread.start()

    def loop_stop(self) -> None:
        self._stop.set()

    def disconnect(self) -> None:
        self._stop.set()
        try:
            self._send_packet(DISCONNECT, b"")
        except Exception:
            pass
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def subscribe(self, topic: str, qos: int = 0) -> Tuple[int, int]:
        mid = next(self._mids)
        self._subs[topic] = qos
        self._send_packet(SUBSCRIBE,
                          struct.pack(">H", mid) + encode_str(topic)
                          + bytes([qos]))
        return MQTT_ERR_SUCCESS, mid

    def unsubscribe(self, topic: str) -> None:
        self._subs.pop(topic, None)
        mid = next(self._mids)
        self._send_packet(UNSUBSCRIBE,
                          struct.pack(">H", mid) + encode_str(topic))

    def message_callback_add(self, topic_filter: str, cb: Callable) -> None:
        self._callbacks.append((topic_filter, cb))

    def message_callback_remove(self, topic_filter: str) -> None:
        self._callbacks = [(f, c) for f, c in self._callbacks
                           if f != topic_filter]

    def publish(self, topic: str, payload: Any = b"", qos: int = 0,
                retain: bool = False) -> _PublishInfo:
        if isinstance(payload, str):
            payload = payload.encode()
        payload = bytes(payload or b"")
        flags = (qos << 1) | (1 if retain else 0)
        var = encode_str(topic)
        if qos > 0:
            var += struct.pack(">H", next(self._mids) & 0xFFFF or 1)
        try:
            self._send_packet(PUBLISH | flags, var + payload)
            return _PublishInfo(MQTT_ERR_SUCCESS)
        except Exception as exc:
            logger.warning("mqtt publish failed: %s", exc)
            return _PublishInfo(1)

    # ---------------------------------------------------------------- wire
    def _send_packet(self, first: int, body: bytes) -> None:
        with self._wlock:
            if self._sock is None:
                raise ConnectionError("mqtt not connected")
            self._sock.sendall(bytes([first]) + encode_varint(len(body)) + body)

    def _read_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("mqtt connection closed")
            out += chunk
        return out

    def _read_packet(self) -> Tuple[int, bytes]:
        first = self._read_exact(1)[0]
        mult, length = 1, 0
        while True:
            b = self._read_exact(1)[0]
            length += (b & 0x7F) * mult
            if not (b & 0x80):
                break
            mult *= 128
        return first, self._read_exact(length) if length else b""

    def _loop(self) -> None:
        last_ping = time.monotonic()
        while not self._stop.is_set():
            try:
                self._sock.settimeout(1.0)
                try:
                    typ, body = self._read_packet()
                except socket.timeout:
                    if time.monotonic() - last_ping > self._keepalive / 2:
                        self._send_packet(PINGREQ, b"")
                        last_ping = time.monotonic()
                    continue
                self._handle(typ, body)
            except Exception as exc:
                if self._stop.is_set():
                    return
                logger.warning("mqtt reconnect: %s", exc)
                self._reconnect()

    def _reconnect(self) -> None:
        # jittered exponential backoff (utils/backoff.py): a broker
        # restart must not make every client redial on the same beat
        from ..utils.backoff import Backoff

        bo = Backoff(base_s=0.5, cap_s=30.0)
        while not self._stop.is_set():
            try:
                self._dial()
                for topic, qos in list(self._subs.items()):
                    self.subscribe(topic, qos)
                return
            except Exception:
                if bo.wait(self._stop):
                    return

    def _handle(self, typ: int, body: bytes) -> None:
        kind = typ & 0xF0
        if kind == PUBLISH:
            qos = (typ >> 1) & 0x03
            tlen = struct.unpack(">H", body[:2])[0]
            topic = body[2:2 + tlen].decode("utf-8", errors="replace")
            pos = 2 + tlen
            mid = 0
            if qos > 0:
                mid = struct.unpack(">H", body[pos:pos + 2])[0]
                pos += 2
                self._send_packet(PUBACK, struct.pack(">H", mid))
            msg = _Msg(topic, body[pos:], qos, mid)
            for filt, cb in list(self._callbacks):
                if topic_matches(filt, topic):
                    try:
                        cb(self, None, msg)
                    except Exception as exc:
                        logger.warning("mqtt callback error: %s", exc)
            if self.on_message is not None:
                try:
                    self.on_message(self, None, msg)
                except Exception as exc:
                    logger.warning("mqtt on_message error: %s", exc)
        # CONNACK/SUBACK/UNSUBACK/PUBACK/PINGRESP need no action here
