"""Native columnar JSON decode — loader for native/jsoncol.cpp (ekjsoncol).

The ingest hot path hands a broker drain (list of raw JSON object payloads)
plus the stream's typed schema to the C decoder, which fills numpy columns +
validity masks in one pass (repeated strings interned). Falls back to the
Python decode+from_messages chain when the extension is unavailable, the
schema has non-scalar fields, or the C parser raises Fallback (int64
overflow, non-bytes payloads).

Reference analogue: the schema-aware fastjson converter
(/root/reference/internal/converter/json) that feeds SliceTuple columns.
"""
from __future__ import annotations

import os
import subprocess
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..data.types import DataType, Schema
from ..utils.infra import logger

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_lock = threading.Lock()
_mod = None
_tried = False
_build_started = False

_FIELD_TYPES = {
    DataType.FLOAT: 0,
    DataType.BIGINT: 1,
    DataType.BOOLEAN: 2,
    DataType.STRING: 3,
}


def _build() -> bool:
    try:
        native = os.path.abspath(_NATIVE_DIR)
        scratch = f"build.tmp.jc.{os.getpid()}"
        import sys

        subprocess.run(
            ["make", "-C", native, f"BUILD={scratch}",
             f"PYTHON={sys.executable}", f"{scratch}/ekjsoncol.so"],
            capture_output=True, timeout=180, check=True,
        )
        os.makedirs(os.path.join(native, "build"), exist_ok=True)
        os.replace(os.path.join(native, scratch, "ekjsoncol.so"),
                   os.path.join(native, "build", "ekjsoncol.so"))
        try:
            os.rmdir(os.path.join(native, scratch))
        except OSError:
            pass
        return True
    except Exception as e:
        logger.warning("ekjsoncol build failed (%s); python decode path", e)
        return False


def ensure_native(background: bool = True) -> None:
    """Kick off the native build once per process; never blocks ingest."""
    global _build_started
    so = os.path.abspath(os.path.join(_NATIVE_DIR, "build", "ekjsoncol.so"))
    with _lock:
        if os.path.exists(so) or _tried or _build_started:
            return
        _build_started = True
    if background:
        threading.Thread(target=_build, daemon=True,
                         name="ekjsoncol-build").start()
    else:
        _build()


def _load():
    global _mod, _tried
    with _lock:
        if _tried:
            return _mod
        so = os.path.abspath(
            os.path.join(_NATIVE_DIR, "build", "ekjsoncol.so"))
        if not os.path.exists(so):
            return None  # keep probing; a background build may land
        try:
            import importlib.util

            spec = importlib.util.spec_from_file_location("ekjsoncol", so)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _mod = mod
        except Exception as e:
            logger.warning("ekjsoncol load failed (%s); python decode", e)
            _mod = None
        _tried = True
        return _mod


def schema_field_spec(schema: Optional[Schema]):
    """((name, ctype), ...) when every schema field is C-decodable, else
    None (caller uses the Python path)."""
    if schema is None or schema.schemaless or not schema.fields:
        return None
    spec = []
    for f in schema.fields:
        t = _FIELD_TYPES.get(f.type)
        if t is None:
            return None
        spec.append((f.name, t))
    return tuple(spec)


def native_module():
    """The loaded ekjsoncol module, or None. Does NOT trigger a build —
    callers that can start one use ensure_native(); everything else (the
    key-slot encode fast path in ops/keytable.py) just rides whatever a
    source already built."""
    return _load()


def has_keytab() -> bool:
    """True when the loaded native decoder carries the persistent key-slot
    table API (a stale prebuilt .so may predate it)."""
    mod = _load()
    return mod is not None and hasattr(mod, "keytab_encode")


def decode_columns(
    payloads: List[bytes], field_spec, shards: int = 1,
) -> Optional[Tuple[Dict[str, Any], Dict[str, Any], Any]]:
    """(columns, valid, bad) via the native decoder, or None to fall back.
    shards > 1 splits the GIL-free parse pass across that many native
    threads (contiguous payload slices into one shared allocation) —
    output is byte-identical for any shard count."""
    mod = _load()
    if mod is None:
        return None
    try:
        try:
            return mod.decode(list(payloads), field_spec, int(shards))
        except TypeError:
            # stale prebuilt .so without the shard API
            return mod.decode(list(payloads), field_spec)
    except mod.Fallback:
        return None
    except Exception as e:
        logger.warning("ekjsoncol decode error (%s); python fallback", e)
        return None
