"""In-process memory pub/sub — analogue of eKuiper's memory source/sink
(internal/io/memory/pubsub/manager.go:45-130): topic-based, wildcard
subscriptions (`+` single level, `#` multi level), the rule-pipeline
mechanism (rule A's memory sink feeds rule B's memory stream).
"""
from __future__ import annotations

import re
import threading
from typing import Any, Callable, Dict, List, Optional

from .contract import Sink, Source

_lock = threading.RLock()


def _topic_regex(pattern: str) -> re.Pattern:
    parts = pattern.split("/")
    out = []
    for i, p in enumerate(parts):
        if p == "#":
            out.append(".*")
            break
        if p == "+":
            out.append("[^/]+")
        else:
            out.append(re.escape(p))
    return re.compile("^" + "/".join(out) + "$")


class _Sub:
    def __init__(self, pattern: str, fn: Callable[[str, Any], None]) -> None:
        self.pattern = pattern
        self.regex = _topic_regex(pattern)
        self.fn = fn


_subs: List[_Sub] = []


def publish(topic: str, payload: Any) -> None:
    with _lock:
        targets = [s.fn for s in _subs if s.regex.match(topic)]
    for fn in targets:
        fn(topic, payload)


def subscribe(pattern: str, fn: Callable[[str, Any], None]) -> Callable[[], None]:
    sub = _Sub(pattern, fn)
    with _lock:
        _subs.append(sub)

    def unsubscribe() -> None:
        with _lock:
            try:
                _subs.remove(sub)
            except ValueError:
                pass

    return unsubscribe


def reset() -> None:
    with _lock:
        _subs.clear()


class MemorySource(Source):
    def __init__(self) -> None:
        self.topic = ""
        self._unsub: Optional[Callable[[], None]] = None

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.topic = datasource or props.get("topic", "")

    def open(self, ingest) -> None:
        self._unsub = subscribe(
            self.topic, lambda topic, payload: ingest(payload, {"topic": topic})
        )

    def close(self) -> None:
        if self._unsub is not None:
            self._unsub()


class MemorySink(Sink):
    def __init__(self) -> None:
        self.topic = ""

    def configure(self, props: Dict[str, Any]) -> None:
        self.topic = props.get("topic", "")

    def collect(self, item: Any) -> None:
        publish(self.topic, item)


class MemoryLookupSource:
    """Lookup table over memory topic updates keyed by a field
    (analogue internal/io/memory lookup)."""

    def __init__(self) -> None:
        self.topic = ""
        self.key = ""
        self._table: Dict[Any, Dict[str, Any]] = {}
        self._unsub: Optional[Callable[[], None]] = None

    def configure(self, datasource: str, props: Dict[str, Any]) -> None:
        self.topic = datasource or props.get("topic", "")
        self.key = props.get("key", "")

    def open(self) -> None:
        def on_msg(topic: str, payload: Any) -> None:
            rows = payload if isinstance(payload, list) else [payload]
            for row in rows:
                if isinstance(row, dict) and self.key in row:
                    self._table[row[self.key]] = row

        self._unsub = subscribe(self.topic, on_msg)

    def lookup(self, fields, keys, values) -> List[Dict[str, Any]]:
        if len(keys) == 1 and keys[0] == self.key:
            row = self._table.get(values[0])
            return [row] if row is not None else []
        out = []
        for row in self._table.values():
            if all(row.get(k) == v for k, v in zip(keys, values)):
                out.append(row)
        return out

    def close(self) -> None:
        if self._unsub is not None:
            self._unsub()
