"""TDengine 3.x sink — analogue of the reference's tdengine3 extension
(extensions/impl/tdengine3/tdengine3.go).

Statement construction mirrors the reference exactly (its own unit tests
are the spec: ts column first with `now` unless provideTs, string values
double-quoted, tagFields -> USING <sTable> TAGS(...), fields prop selects
and orders columns, otherwise all non-ts/non-tag row keys).

Transport divergence (documented): the reference links the taosWS CGo/
websocket driver; this image has no TDengine client, so statements execute
over taosAdapter's REST endpoint — `POST /rest/sql/<db>` with HTTP Basic
auth — which every TDengine 3.x deployment ships on port 6041.
"""
from __future__ import annotations

import base64
import json
import urllib.request
from typing import Any, Dict, List, Optional

from ..utils.infra import EngineError
from .contract import Sink


def _stmt_parts(cfg: Dict[str, Any], row: Dict[str, Any]) -> tuple:
    """One row -> (prefix, values_group) with tdengine3.go:140-215
    semantics; prefix is everything up to (excluding) ` values`, so rows
    sharing a prefix can batch into one multi-row statement."""
    table = cfg.get("table", "")
    s_table = cfg.get("sTable", "")
    ts_field = cfg.get("tsFieldName", "ts")
    tag_fields: List[str] = cfg.get("tagFields") or []
    fields: List[str] = cfg.get("fields") or []
    keys: List[str] = []
    vals: List[str] = []

    def fmt(v: Any) -> str:
        if isinstance(v, str):
            # escape for TDengine double-quoted literals — unescaped
            # quotes break the statement and open SQL injection via
            # row data
            esc = v.replace("\\", "\\\\").replace('"', '\\"')
            return f'"{esc}"'
        return f"{v}"

    if cfg.get("provideTs"):
        if ts_field not in row:
            raise EngineError(f"timestamp field not found : {ts_field}")
        keys.append(ts_field)
        vals.append(f"{row[ts_field]}")
    else:
        keys.append(ts_field)
        vals.append("now")
    tags = [fmt(row.get(t)) for t in tag_fields]
    data_keys = fields if fields else sorted(row)
    for k in data_keys:
        if k == ts_field or k in tag_fields:
            continue
        if k not in row:
            raise EngineError(f"field not found : {k}")
        keys.append(k)
        vals.append(fmt(row[k]))
    prefix = f"INSERT INTO {table} ({','.join(keys)})"
    if s_table:
        prefix += f" USING {s_table}"
    if tags:
        prefix += f" TAGS({','.join(tags)})"
    return prefix, f"({','.join(vals)})"


def build_insert(cfg: Dict[str, Any], row: Dict[str, Any]) -> str:
    """One row -> INSERT statement (tdengine3.go:140-215 semantics)."""
    prefix, values = _stmt_parts(cfg, row)
    return f"{prefix} values {values}"


#: statement size cap — TDengine 3.x rejects SQL past ~1MB (maxSQLLength);
#: stay well under it so a huge window emit chunks instead of failing whole
_MAX_STMT_BYTES = 512 * 1024


def build_insert_many(cfg: Dict[str, Any],
                      rows: List[Dict[str, Any]]) -> List[str]:
    """A window emit's rows -> the fewest multi-row INSERT statements:
    consecutive-prefix runs batch into `INSERT INTO t (...) values
    (...)(...)` — TDengine's native multi-row form — instead of one
    HTTP round trip per row (VERDICT r5 weak #5). Rows with different
    column sets or tag values (distinct prefixes) keep their own
    statement; statements also split at _MAX_STMT_BYTES so one oversized
    emit cannot exceed the server's SQL length limit; row order is
    preserved within and across statements."""
    stmts: List[str] = []
    cur_prefix: Optional[str] = None
    cur_vals: List[str] = []
    cur_len = 0

    def cut() -> None:
        if cur_prefix is not None:
            stmts.append(f"{cur_prefix} values {''.join(cur_vals)}")

    for row in rows:
        prefix, values = _stmt_parts(cfg, row)
        if (prefix == cur_prefix
                and cur_len + len(values) <= _MAX_STMT_BYTES):
            cur_vals.append(values)
            cur_len += len(values)
        else:
            cut()
            cur_prefix, cur_vals = prefix, [values]
            cur_len = len(prefix) + len(values) + 8
    cut()
    return stmts


class Tdengine3Sink(Sink):
    def __init__(self) -> None:
        self.cfg: Dict[str, Any] = {}
        self.url = ""
        self._auth = ""

    def configure(self, props: Dict[str, Any]) -> None:
        host = props.get("host", "localhost")
        port = int(props.get("port", 6041))  # taosAdapter REST default
        user = props.get("user", "root")
        password = props.get("password", "taosdata")
        database = props.get("database", "")
        if not database:
            raise EngineError("tdengine3 sink requires database")
        if not props.get("table"):
            raise EngineError("tdengine3 sink requires table")
        self.cfg = dict(props)
        self.url = f"http://{host}:{port}/rest/sql/{database}"
        self._auth = "Basic " + base64.b64encode(
            f"{user}:{password}".encode()).decode()

    def collect(self, item: Any) -> None:
        rows = item if isinstance(item, list) else [item]
        data_field = self.cfg.get("dataField", "")
        decoded: List[Dict[str, Any]] = []
        for row in rows:
            if isinstance(row, (bytes, str)):
                row = json.loads(row)
            if data_field:
                row = row.get(data_field, row)
            decoded.append(row)
        # one multi-row statement per consecutive-prefix run: a 1000-row
        # window emit is one POST to taosAdapter, not 1000 sequential ones
        for stmt in build_insert_many(self.cfg, decoded):
            self._exec(stmt)

    def _exec(self, stmt: str) -> None:
        req = urllib.request.Request(
            self.url, data=stmt.encode(),
            headers={"Authorization": self._auth,
                     "Content-Type": "text/plain"})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = json.loads(resp.read() or b"{}")
        except Exception as e:
            raise EngineError(f"tdengine3 exec failed: {e}")
        # taosAdapter: {"code": 0, ...} on success
        if body.get("code", 0) != 0:
            raise EngineError(
                f"tdengine3 error {body.get('code')}: {body.get('desc')}")

    def close(self) -> None:
        pass
