"""TDengine 3.x sink — analogue of the reference's tdengine3 extension
(extensions/impl/tdengine3/tdengine3.go).

Statement construction mirrors the reference exactly (its own unit tests
are the spec: ts column first with `now` unless provideTs, string values
double-quoted, tagFields -> USING <sTable> TAGS(...), fields prop selects
and orders columns, otherwise all non-ts/non-tag row keys).

Transport divergence (documented): the reference links the taosWS CGo/
websocket driver; this image has no TDengine client, so statements execute
over taosAdapter's REST endpoint — `POST /rest/sql/<db>` with HTTP Basic
auth — which every TDengine 3.x deployment ships on port 6041.
"""
from __future__ import annotations

import base64
import json
import urllib.request
from typing import Any, Dict, List, Optional

from ..utils.infra import EngineError
from .contract import Sink


def build_insert(cfg: Dict[str, Any], row: Dict[str, Any]) -> str:
    """One row -> INSERT statement (tdengine3.go:140-215 semantics)."""
    table = cfg.get("table", "")
    s_table = cfg.get("sTable", "")
    ts_field = cfg.get("tsFieldName", "ts")
    tag_fields: List[str] = cfg.get("tagFields") or []
    fields: List[str] = cfg.get("fields") or []
    keys: List[str] = []
    vals: List[str] = []

    def fmt(v: Any) -> str:
        if isinstance(v, str):
            # escape for TDengine double-quoted literals — unescaped
            # quotes break the statement and open SQL injection via
            # row data
            esc = v.replace("\\", "\\\\").replace('"', '\\"')
            return f'"{esc}"'
        return f"{v}"

    if cfg.get("provideTs"):
        if ts_field not in row:
            raise EngineError(f"timestamp field not found : {ts_field}")
        keys.append(ts_field)
        vals.append(f"{row[ts_field]}")
    else:
        keys.append(ts_field)
        vals.append("now")
    tags = [fmt(row.get(t)) for t in tag_fields]
    data_keys = fields if fields else sorted(row)
    for k in data_keys:
        if k == ts_field or k in tag_fields:
            continue
        if k not in row:
            raise EngineError(f"field not found : {k}")
        keys.append(k)
        vals.append(fmt(row[k]))
    stmt = f"INSERT INTO {table} ({','.join(keys)})"
    if s_table:
        stmt += f" USING {s_table}"
    if tags:
        stmt += f" TAGS({','.join(tags)})"
    stmt += f" values ({','.join(vals)})"
    return stmt


class Tdengine3Sink(Sink):
    def __init__(self) -> None:
        self.cfg: Dict[str, Any] = {}
        self.url = ""
        self._auth = ""

    def configure(self, props: Dict[str, Any]) -> None:
        host = props.get("host", "localhost")
        port = int(props.get("port", 6041))  # taosAdapter REST default
        user = props.get("user", "root")
        password = props.get("password", "taosdata")
        database = props.get("database", "")
        if not database:
            raise EngineError("tdengine3 sink requires database")
        if not props.get("table"):
            raise EngineError("tdengine3 sink requires table")
        self.cfg = dict(props)
        self.url = f"http://{host}:{port}/rest/sql/{database}"
        self._auth = "Basic " + base64.b64encode(
            f"{user}:{password}".encode()).decode()

    def collect(self, item: Any) -> None:
        rows = item if isinstance(item, list) else [item]
        data_field = self.cfg.get("dataField", "")
        for row in rows:
            if isinstance(row, (bytes, str)):
                row = json.loads(row)
            if data_field:
                row = row.get(data_field, row)
            self._exec(build_insert(self.cfg, row))

    def _exec(self, stmt: str) -> None:
        req = urllib.request.Request(
            self.url, data=stmt.encode(),
            headers={"Authorization": self._auth,
                     "Content-Type": "text/plain"})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = json.loads(resp.read() or b"{}")
        except Exception as e:
            raise EngineError(f"tdengine3 exec failed: {e}")
        # taosAdapter: {"code": 0, ...} on success
        if body.get("code", 0) != 0:
            raise EngineError(
                f"tdengine3 error {body.get('code')}: {body.get('desc')}")

    def close(self) -> None:
        pass
