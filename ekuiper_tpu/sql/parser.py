"""SQL parser — analogue of eKuiper's internal/xsql/parser.go (Parser.Parse
at parser.go:150, ParseCreateStmt at :1158, window validation at :1047-1119).

Recursive-descent with precedence climbing (precedence table mirrors
pkg/ast/token.go:303-318). Windows are parsed as table functions inside
GROUP BY — TUMBLINGWINDOW(ss, 10) etc. — and converted to ast.Window with the
same arity rules as the reference's validateWindows/ConvertToWindows.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..data.types import DataType
from ..utils.infra import ParseError
from . import ast
from .lexer import (
    EOF, IDENT, INTEGER, KEYWORD, NUMBER, OP, STRING, TIME_UNITS, Token,
    TokenStream,
)

WINDOW_FUNCS = {
    "tumblingwindow": ast.WindowType.TUMBLING_WINDOW,
    "hoppingwindow": ast.WindowType.HOPPING_WINDOW,
    "slidingwindow": ast.WindowType.SLIDING_WINDOW,
    "sessionwindow": ast.WindowType.SESSION_WINDOW,
    "countwindow": ast.WindowType.COUNT_WINDOW,
    "statewindow": ast.WindowType.STATE_WINDOW,
}

_TYPE_NAMES = {
    "BIGINT": DataType.BIGINT,
    "FLOAT": DataType.FLOAT,
    "STRING": DataType.STRING,
    "BYTEA": DataType.BYTEA,
    "DATETIME": DataType.DATETIME,
    "BOOLEAN": DataType.BOOLEAN,
    "ARRAY": DataType.ARRAY,
    "STRUCT": DataType.STRUCT,
}


class Parser:
    def __init__(self, sql: str) -> None:
        self.ts = TokenStream(sql)
        self._func_id = 0

    # ------------------------------------------------------------- entry points
    def parse(self) -> ast.Statement:
        tok = self.ts.peek()
        if tok.kind == KEYWORD:
            if tok.text == "SELECT":
                stmt = self.parse_select()
            elif tok.text == "CREATE":
                stmt = self.parse_create()
            elif tok.text == "SHOW":
                stmt = self.parse_show()
            elif tok.text in ("DESCRIBE", "DESC"):
                stmt = self.parse_describe()
            elif tok.text == "DROP":
                stmt = self.parse_drop()
            elif tok.text == "EXPLAIN":
                stmt = self.parse_explain()
            else:
                raise ParseError(f"unexpected keyword {tok.text} at start of statement")
        else:
            raise ParseError(f"expected statement but found {tok.text!r}")
        self.ts.accept(OP, ";")
        if self.ts.peek().kind != EOF:
            extra = self.ts.peek()
            raise ParseError(f"unexpected trailing input {extra.text!r} at {extra.pos}")
        return stmt

    # ----------------------------------------------------------------- SELECT
    def parse_select(self) -> ast.SelectStatement:
        self.ts.expect(KEYWORD, "SELECT")
        stmt = ast.SelectStatement()
        stmt.fields = self.parse_fields()
        if self.ts.accept(KEYWORD, "FROM"):
            stmt.sources.append(self.parse_table())
            while True:
                join = self.parse_join()
                if join is None:
                    break
                stmt.joins.append(join)
        else:
            raise ParseError("SELECT requires a FROM clause")
        if self.ts.accept(KEYWORD, "WHERE"):
            stmt.condition = self.parse_expr()
        if self.ts.accept(KEYWORD, "GROUP"):
            self.ts.expect(KEYWORD, "BY")
            self._parse_dimensions(stmt)
        if self.ts.accept(KEYWORD, "HAVING"):
            stmt.having = self.parse_expr()
        if self.ts.accept(KEYWORD, "ORDER"):
            self.ts.expect(KEYWORD, "BY")
            stmt.sorts = self.parse_sort_fields()
        if self.ts.accept(KEYWORD, "LIMIT"):
            lim = self.ts.expect(INTEGER)
            stmt.limit = int(lim.text)
        return stmt

    def parse_fields(self) -> List[ast.Field]:
        fields: List[ast.Field] = []
        while True:
            fields.append(self.parse_field(len(fields)))
            if not self.ts.accept(OP, ","):
                break
        return fields

    def parse_field(self, idx: int) -> ast.Field:
        expr = self.parse_expr()
        alias = ""
        if self.ts.accept(KEYWORD, "AS"):
            alias = self._ident_like()
        invisible = bool(self.ts.accept(KEYWORD, "INVISIBLE"))
        name = self._derive_name(expr, idx)
        return ast.Field(expr=expr, name=name, alias=alias, invisible=invisible)

    @staticmethod
    def _derive_name(expr: ast.Expr, idx: int) -> str:
        if isinstance(expr, ast.FieldRef):
            return expr.name
        if isinstance(expr, ast.Call):
            return expr.name
        if isinstance(expr, ast.Wildcard):
            return "*"
        if isinstance(expr, ast.ArrowExpr):
            return expr.name
        return f"kuiper_field_{idx}"

    def _ident_like(self) -> str:
        tok = self.ts.peek()
        if tok.kind == IDENT:
            return self.ts.next().text
        if tok.kind == KEYWORD:  # allow keywords as aliases (e.g. AS end)
            return self.ts.next().text.lower()
        raise ParseError(f"expected identifier but found {tok.text!r} at {tok.pos}")

    def parse_table(self) -> ast.Table:
        name = self._ident_like()
        alias = ""
        if self.ts.accept(KEYWORD, "AS"):
            alias = self._ident_like()
        elif self.ts.peek().kind == IDENT and not self.ts.at_keyword():
            # bare alias: FROM demo d
            alias = self.ts.next().text
        return ast.Table(name=name, alias=alias)

    def parse_join(self) -> Optional[ast.Join]:
        jt: Optional[ast.JoinType] = None
        if self.ts.accept(KEYWORD, "JOIN"):
            jt = ast.JoinType.INNER
        elif self.ts.at_keyword("INNER", "LEFT", "RIGHT", "FULL", "CROSS"):
            kw = self.ts.next().text
            self.ts.expect(KEYWORD, "JOIN")
            jt = ast.JoinType[kw]
        else:
            return None
        table = self.parse_table()
        on: Optional[ast.Expr] = None
        if self.ts.accept(KEYWORD, "ON"):
            on = self.parse_expr()
        elif jt != ast.JoinType.CROSS:
            raise ParseError(f"{jt.value} JOIN requires an ON clause")
        return ast.Join(table=table, join_type=jt, on=on)

    def _parse_dimensions(self, stmt: ast.SelectStatement) -> None:
        while True:
            expr = self.parse_expr()
            window = self._try_window(expr)
            if window is not None:
                if stmt.window is not None:
                    raise ParseError("at most one window per statement")
                stmt.window = window
            else:
                stmt.dimensions.append(ast.Dimension(expr=expr))
            if not self.ts.accept(OP, ","):
                break

    def _try_window(self, expr: ast.Expr) -> Optional[ast.Window]:
        if not isinstance(expr, ast.Call):
            return None
        wtype = WINDOW_FUNCS.get(expr.name.lower())
        if wtype is None:
            return None
        win = self._convert_window(wtype, expr.args)
        # FILTER(WHERE ...) attached to the window call
        if expr.filter is not None:
            win.filter = expr.filter
        if expr.when is not None:
            win.trigger_condition = expr.when
        return win

    def _convert_window(self, wtype: ast.WindowType, args: List[ast.Expr]) -> ast.Window:
        """Mirrors validateWindows + ConvertToWindows
        (reference: internal/xsql/parser.go:1047-1160)."""
        name = wtype.value
        win = ast.Window(window_type=wtype)
        if wtype == ast.WindowType.STATE_WINDOW:
            if len(args) != 2:
                raise ParseError(f"the arguments for {name} should be 2")
            win.begin_condition, win.emit_condition = args[0], args[1]
            return win
        if wtype == ast.WindowType.COUNT_WINDOW:
            if not args or len(args) > 2:
                raise ParseError(f"invalid parameter count for {name}")
            if not isinstance(args[0], ast.IntegerLiteral) or args[0].val <= 0:
                raise ParseError(f"invalid parameter value for {name}")
            win.length = args[0].val
            if len(args) == 2:
                if not isinstance(args[1], ast.IntegerLiteral) or args[1].val <= 0:
                    raise ParseError(f"invalid parameter value for {name}")
                if args[0].val < args[1].val:
                    raise ParseError(
                        f"the second parameter {args[1].val} should be <= the first {args[0].val}"
                    )
                win.interval = args[1].val
            return win
        expect = {
            ast.WindowType.TUMBLING_WINDOW: (2, 2),
            ast.WindowType.HOPPING_WINDOW: (3, 3),
            ast.WindowType.SESSION_WINDOW: (3, 3),
            ast.WindowType.SLIDING_WINDOW: (2, 3),
        }[wtype]
        if not (expect[0] <= len(args) <= expect[1]):
            raise ParseError(f"the arguments for {name} should be {expect[0]}")
        if not isinstance(args[0], ast.TimeLiteral):
            raise ParseError(
                f"the 1st argument for {name} must be a time unit [dd|hh|mi|ss|ms]"
            )
        for a in args[1:]:
            if not isinstance(a, ast.IntegerLiteral):
                raise ParseError(f"the arguments for {name} must be integer literals")
        win.time_unit = args[0].val
        win.length = args[1].val
        if len(args) > 2:
            if wtype == ast.WindowType.SLIDING_WINDOW:
                win.delay = args[2].val
            else:
                win.interval = args[2].val
        return win

    def parse_sort_fields(self) -> List[ast.SortField]:
        sorts: List[ast.SortField] = []
        while True:
            expr = self.parse_expr()
            sf = ast.SortField(name="", expr=expr)
            if isinstance(expr, ast.FieldRef):
                sf.name, sf.stream = expr.name, expr.stream
            if self.ts.accept(KEYWORD, "DESC"):
                sf.ascending = False
            else:
                self.ts.accept(KEYWORD, "ASC")
            sorts.append(sf)
            if not self.ts.accept(OP, ","):
                break
        return sorts

    # ------------------------------------------------------------ expressions
    def parse_expr(self, min_prec: int = 1) -> ast.Expr:
        lhs = self.parse_unary()
        while True:
            op, prec, negate = self._peek_binary_op()
            if op is None or prec < min_prec:
                return lhs
            self._consume_binary_op(op, negate)
            if op == "BETWEEN":
                lo = self.parse_expr(ast.PRECEDENCE["BETWEEN"] + 1)
                self.ts.expect(KEYWORD, "AND")
                hi = self.parse_expr(ast.PRECEDENCE["BETWEEN"] + 1)
                lhs = ast.BetweenExpr(value=lhs, lo=lo, hi=hi, negate=negate)
            elif op == "IN":
                self.ts.expect(OP, "(")
                values = [self.parse_expr()]
                while self.ts.accept(OP, ","):
                    values.append(self.parse_expr())
                self.ts.expect(OP, ")")
                lhs = ast.InExpr(value=lhs, values=values, negate=negate)
            elif op == "LIKE":
                pattern = self.parse_expr(ast.PRECEDENCE["LIKE"] + 1)
                lhs = ast.LikeExpr(value=lhs, pattern=pattern, negate=negate)
            else:
                rhs = self.parse_expr(prec + 1)
                lhs = ast.BinaryExpr(op=op, lhs=lhs, rhs=rhs)

    def _peek_binary_op(self) -> Tuple[Optional[str], int, bool]:
        tok = self.ts.peek()
        if tok.kind == OP and tok.text in ast.PRECEDENCE:
            return tok.text, ast.PRECEDENCE[tok.text], False
        if tok.kind == KEYWORD:
            if tok.text in ("AND", "OR", "IN", "BETWEEN", "LIKE"):
                return tok.text, ast.PRECEDENCE[tok.text], False
            if tok.text == "NOT":
                nxt = self.ts.peek(1)
                if nxt.kind == KEYWORD and nxt.text in ("IN", "BETWEEN", "LIKE"):
                    return nxt.text, ast.PRECEDENCE[nxt.text], True
        return None, 0, False

    def _consume_binary_op(self, op: str, negate: bool) -> None:
        if negate:
            self.ts.next()  # NOT
        self.ts.next()  # the operator itself

    def parse_unary(self) -> ast.Expr:
        if self.ts.accept(KEYWORD, "NOT"):
            return ast.UnaryExpr(op="NOT", expr=self.parse_unary())
        if self.ts.accept(OP, "-"):
            inner = self.parse_unary()
            if isinstance(inner, ast.IntegerLiteral):
                return ast.IntegerLiteral(-inner.val)
            if isinstance(inner, ast.NumberLiteral):
                return ast.NumberLiteral(-inner.val)
            return ast.UnaryExpr(op="-", expr=inner)
        self.ts.accept(OP, "+")
        return self.parse_postfix()

    def parse_postfix(self) -> ast.Expr:
        expr = self.parse_primary()
        while True:
            if self.ts.accept(OP, "["):
                expr = self._parse_index(expr)
            elif self.ts.accept(OP, "->"):
                name = self._ident_like()
                expr = ast.ArrowExpr(value=expr, name=name)
            elif (
                self.ts.peek().kind == OP
                and self.ts.peek().text == "."
                and not isinstance(expr, (ast.FieldRef, ast.Wildcard))
            ):
                # json path continuation on non-ref values: f(x).y
                self.ts.next()
                expr = ast.ArrowExpr(value=expr, name=self._ident_like())
            else:
                return expr

    def _parse_index(self, value: ast.Expr) -> ast.Expr:
        # a[i], a[i:j], a[:j], a[i:], a[:]
        lo = hi = index = None
        is_slice = False
        if self.ts.accept(OP, ":"):
            is_slice = True
            if not (self.ts.peek().kind == OP and self.ts.peek().text == "]"):
                hi = self.parse_expr()
        else:
            index = self.parse_expr()
            if self.ts.accept(OP, ":"):
                is_slice = True
                lo, index = index, None
                if not (self.ts.peek().kind == OP and self.ts.peek().text == "]"):
                    hi = self.parse_expr()
        self.ts.expect(OP, "]")
        return ast.IndexExpr(value=value, index=index, lo=lo, hi=hi, is_slice=is_slice)

    def parse_primary(self) -> ast.Expr:
        tok = self.ts.peek()
        if tok.kind == INTEGER:
            self.ts.next()
            return ast.IntegerLiteral(int(tok.text))
        if tok.kind == NUMBER:
            self.ts.next()
            return ast.NumberLiteral(float(tok.text))
        if tok.kind == STRING:
            self.ts.next()
            return ast.StringLiteral(tok.text)
        if tok.kind == KEYWORD and tok.text in ("TRUE", "FALSE"):
            self.ts.next()
            return ast.BooleanLiteral(tok.text == "TRUE")
        if tok.kind == KEYWORD and tok.text == "CASE":
            return self.parse_case()
        if tok.kind == OP and tok.text == "*":
            self.ts.next()
            return self._parse_wildcard()
        if tok.kind == OP and tok.text == "(":
            self.ts.next()
            expr = self.parse_expr()
            self.ts.expect(OP, ")")
            return expr
        if tok.kind == IDENT or (
            tok.kind == KEYWORD and tok.text in ("REPLACE", "END", "FILTER")
        ):
            return self._parse_ident_expr()
        raise ParseError(f"unexpected token {tok.text!r} at position {tok.pos}")

    def _parse_wildcard(self) -> ast.Expr:
        wc = ast.Wildcard()
        while True:
            if self.ts.at_keyword("EXCEPT"):
                self.ts.next()
                self.ts.expect(OP, "(")
                wc.except_names.append(self._ident_like())
                while self.ts.accept(OP, ","):
                    wc.except_names.append(self._ident_like())
                self.ts.expect(OP, ")")
            elif self.ts.at_keyword("REPLACE"):
                self.ts.next()
                self.ts.expect(OP, "(")
                while True:
                    expr = self.parse_expr()
                    self.ts.expect(KEYWORD, "AS")
                    alias = self._ident_like()
                    wc.replaces.append(ast.Field(expr=expr, name=alias, alias=alias))
                    if not self.ts.accept(OP, ","):
                        break
                self.ts.expect(OP, ")")
            else:
                return wc

    def _parse_ident_expr(self) -> ast.Expr:
        name = self._ident_like()
        if self.ts.accept(OP, "("):
            return self._parse_call(name)
        stream = ""
        if self.ts.peek().kind == OP and self.ts.peek().text == ".":
            nxt = self.ts.peek(1)
            if nxt.kind == IDENT:
                self.ts.next()
                stream, name = name, self.ts.next().text
            elif nxt.kind == OP and nxt.text == "*":
                self.ts.next()
                self.ts.next()
                return ast.Wildcard(stream=name)  # stream.* — one stream's cols
        return ast.FieldRef(name=name, stream=stream)

    def _parse_call(self, name: str) -> ast.Expr:
        lname = name.lower()
        args: List[ast.Expr] = []
        if not (self.ts.peek().kind == OP and self.ts.peek().text == ")"):
            while True:
                args.append(self._parse_call_arg(lname))
                if not self.ts.accept(OP, ","):
                    break
        self.ts.expect(OP, ")")
        call = ast.Call(name=lname, args=args, func_id=self._func_id)
        self._func_id += 1
        # parse-time arg validation against the function registry, mirroring
        # the reference's parseCall -> binder lookup (parser.go:889)
        if lname not in WINDOW_FUNCS:
            from ..functions import registry as _freg

            fd = _freg.lookup(lname)
            if fd is not None and fd.val is not None:
                err = fd.val(args)
                if err:
                    raise ParseError(f"{lname}: {err}")
        # FILTER ( WHERE expr )
        if self.ts.at_keyword("FILTER"):
            self.ts.next()
            self.ts.expect(OP, "(")
            self.ts.expect(KEYWORD, "WHERE")
            call.filter = self.parse_expr()
            self.ts.expect(OP, ")")
        # OVER ( [PARTITION BY e, ...] [WHEN cond] )
        if self.ts.at_keyword("OVER"):
            self.ts.next()
            self.ts.expect(OP, "(")
            if self.ts.accept(KEYWORD, "PARTITION"):
                self.ts.expect(KEYWORD, "BY")
                call.partition.append(self.parse_expr())
                while self.ts.accept(OP, ","):
                    call.partition.append(self.parse_expr())
            if self.ts.accept(KEYWORD, "WHEN"):
                call.when = self.parse_expr()
            self.ts.expect(OP, ")")
        return call

    def _parse_call_arg(self, func_name: str) -> ast.Expr:
        tok = self.ts.peek()
        # time-unit literal as first arg of window funcs: tumblingwindow(ss, 10)
        if (
            func_name in WINDOW_FUNCS
            and tok.kind == IDENT
            and tok.text.upper() in TIME_UNITS
        ):
            self.ts.next()
            return ast.TimeLiteral(tok.text.upper())
        return self.parse_expr()

    def parse_case(self) -> ast.Expr:
        self.ts.expect(KEYWORD, "CASE")
        value: Optional[ast.Expr] = None
        if not self.ts.at_keyword("WHEN"):
            value = self.parse_expr()
        whens: List[ast.WhenClause] = []
        while self.ts.accept(KEYWORD, "WHEN"):
            cond = self.parse_expr()
            self.ts.expect(KEYWORD, "THEN")
            result = self.parse_expr()
            whens.append(ast.WhenClause(cond=cond, result=result))
        if not whens:
            raise ParseError("CASE requires at least one WHEN clause")
        else_expr: Optional[ast.Expr] = None
        if self.ts.accept(KEYWORD, "ELSE"):
            else_expr = self.parse_expr()
        self.ts.expect(KEYWORD, "END")
        return ast.CaseExpr(value=value, whens=whens, else_expr=else_expr)

    # ------------------------------------------------------------------- DDL
    def parse_create(self) -> ast.StreamStmt:
        self.ts.expect(KEYWORD, "CREATE")
        is_table = False
        if self.ts.accept(KEYWORD, "TABLE"):
            is_table = True
        else:
            self.ts.expect(KEYWORD, "STREAM")
        name = self._ident_like()
        self.ts.expect(OP, "(")
        fields: List[ast.StreamField] = []
        if not (self.ts.peek().kind == OP and self.ts.peek().text == ")"):
            while True:
                fields.append(self._parse_stream_field())
                if not self.ts.accept(OP, ","):
                    break
        self.ts.expect(OP, ")")
        self.ts.expect(KEYWORD, "WITH")
        self.ts.expect(OP, "(")
        options = self._parse_stream_options()
        self.ts.expect(OP, ")")
        return ast.StreamStmt(name=name, fields=fields, options=options, is_table=is_table)

    def _parse_stream_field(self) -> ast.StreamField:
        fname = self._ident_like()
        return ast.StreamField(name=fname, **self._parse_field_type())

    def _parse_field_type(self) -> dict:
        tok = self.ts.peek()
        tname = tok.text.upper() if tok.kind in (IDENT, KEYWORD) else ""
        if tname not in _TYPE_NAMES:
            raise ParseError(f"invalid field type {tok.text!r} at {tok.pos}")
        self.ts.next()
        dt = _TYPE_NAMES[tname]
        if dt == DataType.ARRAY:
            self.ts.expect(OP, "(")
            elem = self._parse_field_type()
            if elem["fields"]:
                # array of struct: keep struct fields on the array field
                out = {"type": dt, "elem_type": elem["type"], "fields": elem["fields"]}
            else:
                out = {"type": dt, "elem_type": elem["type"], "fields": []}
            self.ts.expect(OP, ")")
            return out
        if dt == DataType.STRUCT:
            self.ts.expect(OP, "(")
            subs: List[ast.StreamField] = []
            while True:
                subs.append(self._parse_stream_field())
                if not self.ts.accept(OP, ","):
                    break
            self.ts.expect(OP, ")")
            return {"type": dt, "elem_type": None, "fields": subs}
        return {"type": dt, "elem_type": None, "fields": []}

    def _parse_stream_options(self) -> ast.StreamOptions:
        opts = ast.StreamOptions()
        bool_keys = {"strict_validation", "shared"}
        int_keys = {"retain_size"}
        if self.ts.peek().kind == OP and self.ts.peek().text == ")":
            return opts
        while True:
            key = self._ident_like().lower()
            self.ts.expect(OP, "=")
            tok = self.ts.next()
            if tok.kind == STRING:
                raw = tok.text
            elif tok.kind == KEYWORD and tok.text in ("TRUE", "FALSE"):
                raw = tok.text.lower()
            elif tok.kind in (INTEGER, NUMBER, IDENT):
                raw = tok.text
            else:
                raise ParseError(f"invalid option value {tok.text!r} at {tok.pos}")
            if not hasattr(opts, key):
                raise ParseError(f"unknown stream option {key.upper()}")
            if key in bool_keys:
                setattr(opts, key, raw.lower() in ("true", "1"))
            elif key in int_keys:
                setattr(opts, key, int(raw))
            else:
                setattr(opts, key, raw)
            if not self.ts.accept(OP, ","):
                break
        return opts

    # -------------------------------------------------------------- management
    def parse_show(self) -> ast.ShowStmt:
        self.ts.expect(KEYWORD, "SHOW")
        if self.ts.accept(KEYWORD, "STREAMS"):
            return ast.ShowStmt(target="STREAMS")
        self.ts.expect(KEYWORD, "TABLES")
        return ast.ShowStmt(target="TABLES")

    def parse_describe(self) -> ast.DescribeStmt:
        self.ts.next()  # DESCRIBE | DESC
        target = "TABLE" if self.ts.accept(KEYWORD, "TABLE") else None
        if target is None:
            self.ts.expect(KEYWORD, "STREAM")
            target = "STREAM"
        return ast.DescribeStmt(target=target, name=self._ident_like())

    def parse_drop(self) -> ast.DropStmt:
        self.ts.expect(KEYWORD, "DROP")
        target = "TABLE" if self.ts.accept(KEYWORD, "TABLE") else None
        if target is None:
            self.ts.expect(KEYWORD, "STREAM")
            target = "STREAM"
        return ast.DropStmt(target=target, name=self._ident_like())

    def parse_explain(self) -> ast.ExplainStmt:
        self.ts.expect(KEYWORD, "EXPLAIN")
        target = "TABLE" if self.ts.accept(KEYWORD, "TABLE") else None
        if target is None:
            self.ts.expect(KEYWORD, "STREAM")
            target = "STREAM"
        return ast.ExplainStmt(target=target, name=self._ident_like())


def parse(sql: str) -> ast.Statement:
    """Parse one statement (analogue of xsql.GetStatementFromSql)."""
    return Parser(sql).parse()


def parse_select(sql: str) -> ast.SelectStatement:
    stmt = parse(sql)
    if not isinstance(stmt, ast.SelectStatement):
        raise ParseError("expected a SELECT statement")
    return stmt
