"""SQL lexer — analogue of eKuiper's internal/xsql/lexical.go (Scanner.Scan).

Produces a token stream for the parser. Keywords are case-insensitive;
identifiers keep their case (optionally backtick-quoted to escape keywords).
String literals: double- or single-quoted. Comments: `--` to EOL and /* */.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..utils.infra import ParseError

# token kinds
EOF = "EOF"
IDENT = "IDENT"
INTEGER = "INTEGER"
NUMBER = "NUMBER"
STRING = "STRING"
OP = "OP"  # operators & punctuation, tok.text holds which
KEYWORD = "KEYWORD"

KEYWORDS = {
    "SELECT", "FROM", "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "CROSS", "ON",
    "WHERE", "LIMIT", "GROUP", "ORDER", "HAVING", "BY", "ASC", "DESC",
    "FILTER", "CASE", "WHEN", "THEN", "ELSE", "END", "OVER", "PARTITION",
    "INVISIBLE", "AND", "OR", "NOT", "IN", "BETWEEN", "LIKE", "AS", "TRUE",
    "FALSE", "REPLACE", "EXCEPT",
    # DDL words are plain idents in the reference scanner but keywords here
    # for convenience; the parser treats them contextually
    "CREATE", "DROP", "EXPLAIN", "DESCRIBE", "DESC", "SHOW", "STREAM",
    "TABLE", "STREAMS", "TABLES", "WITH",
}

# time-unit literals inside window calls
TIME_UNITS = {"DD", "HH", "MI", "SS", "MS"}

MULTI_OPS = ["<=", ">=", "!=", "<>", "->"]
SINGLE_OPS = "+-*/%&|^=<>[](),.#:;"


@dataclass
class Token:
    kind: str
    text: str
    pos: int

    def __repr__(self) -> str:
        return f"{self.kind}({self.text!r})"


def tokenize(sql: str) -> List[Token]:
    tokens: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and i + 1 < n and sql[i + 1] == "*":
            j = sql.find("*/", i + 2)
            if j < 0:
                raise ParseError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    # "1." followed by non-digit is int + DOT (json path)
                    if j + 1 < n and sql[j + 1].isdigit():
                        seen_dot = True
                        j += 1
                    else:
                        break
                elif ch in "eE" and not seen_exp and j + 1 < n and (
                    sql[j + 1].isdigit()
                    or (sql[j + 1] in "+-" and j + 2 < n and sql[j + 2].isdigit())
                ):
                    seen_exp = True
                    j += 2 if sql[j + 1] in "+-" else 1
                else:
                    break
            text = sql[i:j]
            kind = NUMBER if (seen_dot or seen_exp) else INTEGER
            tokens.append(Token(kind, text, i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            text = sql[i:j]
            upper = text.upper()
            if upper in KEYWORDS:
                tokens.append(Token(KEYWORD, upper, i))
            else:
                tokens.append(Token(IDENT, text, i))
            i = j
            continue
        if c == "`":
            j = sql.find("`", i + 1)
            if j < 0:
                raise ParseError(f"unterminated quoted identifier at {i}")
            tokens.append(Token(IDENT, sql[i + 1:j], i))
            i = j + 1
            continue
        if c in ("'", '"'):
            quote = c
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "\\" and j + 1 < n:
                    esc = sql[j + 1]
                    buf.append({"n": "\n", "t": "\t", "r": "\r"}.get(esc, esc))
                    j += 2
                elif sql[j] == quote:
                    break
                else:
                    buf.append(sql[j])
                    j += 1
            if j >= n:
                raise ParseError(f"unterminated string at {i}")
            tokens.append(Token(STRING, "".join(buf), i))
            i = j + 1
            continue
        matched = False
        for op in MULTI_OPS:
            if sql.startswith(op, i):
                tokens.append(Token(OP, "!=" if op == "<>" else op, i))
                i += len(op)
                matched = True
                break
        if matched:
            continue
        if c in SINGLE_OPS:
            tokens.append(Token(OP, c, i))
            i += 1
            continue
        raise ParseError(f"illegal character {c!r} at position {i}")
    tokens.append(Token(EOF, "", n))
    return tokens


class TokenStream:
    def __init__(self, sql: str) -> None:
        self.sql = sql
        self.tokens = tokenize(sql)
        self.i = 0

    def peek(self, ahead: int = 0) -> Token:
        j = min(self.i + ahead, len(self.tokens) - 1)
        return self.tokens[j]

    def next(self) -> Token:
        tok = self.tokens[self.i]
        if self.i < len(self.tokens) - 1:
            self.i += 1
        return tok

    def accept(self, kind: str, text: Optional[str] = None) -> Optional[Token]:
        tok = self.peek()
        if tok.kind == kind and (text is None or tok.text == text):
            return self.next()
        return None

    def expect(self, kind: str, text: Optional[str] = None) -> Token:
        tok = self.accept(kind, text)
        if tok is None:
            got = self.peek()
            want = text or kind
            raise ParseError(
                f"expected {want} but found {got.text or got.kind!r} at position {got.pos}"
            )
        return tok

    def at_keyword(self, *words: str) -> bool:
        tok = self.peek()
        return tok.kind == KEYWORD and tok.text in words
