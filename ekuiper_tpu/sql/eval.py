"""Row-path expression evaluator — analogue of eKuiper's ValuerEval tree
interpreter (reference: internal/xsql/valuer.go:289 Eval, :574 evalBinaryExpr).

This is the *fallback* path: per-row interpretation for expressions the
vectorized compiler can't handle (and for joins/small collections). The hot
path compiles expressions to whole-batch numpy/JAX computations instead
(sql/compiler.py).
"""
from __future__ import annotations

import fnmatch
import re
from typing import Any, Callable, Dict, List, Optional

from ..data import cast
from ..data.rows import GroupedTuples, Row
from ..functions import registry
from ..functions.context import FunctionContext
from ..utils.infra import RuntimeError_
from . import ast


class EvalError(RuntimeError_):
    pass


class Evaluator:
    """Evaluates expressions against a single Row.

    `func_states` maps func_id -> per-instance state dict (stateful funcs);
    owned by the operator so state survives across rows/batches and is
    checkpointable.
    """

    def __init__(
        self,
        rule_id: str = "",
        func_states: Optional[Dict[int, Dict[str, Any]]] = None,
        window_range=None,
        keyed_state=None,
        trigger_time: int = 0,
    ) -> None:
        self.rule_id = rule_id
        self.func_states = func_states if func_states is not None else {}
        self.window_range = window_range
        self.keyed_state = keyed_state
        self.trigger_time = trigger_time

    # ------------------------------------------------------------------ core
    def eval(self, expr: ast.Expr, row: Optional[Row]) -> Any:
        m = getattr(self, "_eval_" + type(expr).__name__, None)
        if m is None:
            raise EvalError(f"cannot evaluate {type(expr).__name__}")
        return m(expr, row)

    def eval_condition(self, expr: ast.Expr, row: Optional[Row]) -> bool:
        v = self.eval(expr, row)
        return v is True

    # --------------------------------------------------------------- literals
    def _eval_IntegerLiteral(self, e: ast.IntegerLiteral, row) -> Any:
        return e.val

    def _eval_NumberLiteral(self, e: ast.NumberLiteral, row) -> Any:
        return e.val

    def _eval_StringLiteral(self, e: ast.StringLiteral, row) -> Any:
        return e.val

    def _eval_BooleanLiteral(self, e: ast.BooleanLiteral, row) -> Any:
        return e.val

    def _eval_TimeLiteral(self, e: ast.TimeLiteral, row) -> Any:
        return e.val

    def _eval_Wildcard(self, e: ast.Wildcard, row) -> Any:
        if row is None:
            return {}
        if e.stream and hasattr(row, "tuples"):
            # stream.* over a join row: only that stream's columns
            out: Dict[str, Any] = {}
            for t in row.tuples:
                if t.emitter == e.stream:
                    out.update(t.all_values())
        else:
            out = row.all_values()
        for name in e.except_names:
            out.pop(name, None)
        for f in e.replaces:
            out[f.alias] = self.eval(f.expr, row)
        return out

    # ------------------------------------------------------------- references
    def _eval_FieldRef(self, e: ast.FieldRef, row) -> Any:
        if row is None:
            return None
        v, _ = row.value(e.name, e.stream)
        return v

    def _eval_MetaRef(self, e: ast.MetaRef, row) -> Any:
        if row is None or not hasattr(row, "meta"):
            return None
        v, _ = row.meta(e.name)
        return v

    # -------------------------------------------------------------- operators
    def _eval_UnaryExpr(self, e: ast.UnaryExpr, row) -> Any:
        v = self.eval(e.expr, row)
        if e.op == "NOT":
            if v is None:
                return None
            return not cast.to_bool(v)
        if e.op == "-":
            if v is None:
                return None
            return -v
        raise EvalError(f"unknown unary operator {e.op}")

    def _eval_BinaryExpr(self, e: ast.BinaryExpr, row) -> Any:
        op = e.op
        if op == "AND":
            lhs = self.eval(e.lhs, row)
            if lhs is False:
                return False
            rhs = self.eval(e.rhs, row)
            if rhs is False:
                return False
            if lhs is None or rhs is None:
                return None
            return cast.to_bool(lhs) and cast.to_bool(rhs)
        if op == "OR":
            lhs = self.eval(e.lhs, row)
            if lhs is True:
                return True
            rhs = self.eval(e.rhs, row)
            if rhs is True:
                return True
            if lhs is None or rhs is None:
                return None
            return cast.to_bool(lhs) or cast.to_bool(rhs)

        lhs = self.eval(e.lhs, row)
        rhs = self.eval(e.rhs, row)
        if op in ("=", "!="):
            if lhs is None or rhs is None:
                # reference: null = null is true, null = x is false
                eq = lhs is None and rhs is None
                return eq if op == "=" else not eq
            c = cast.compare(lhs, rhs)
            if c is None:
                eq = lhs == rhs
            else:
                eq = c == 0
            return eq if op == "=" else not eq
        if op in ("<", "<=", ">", ">="):
            c = cast.compare(lhs, rhs)
            if c is None:
                return False
            return {"<": c < 0, "<=": c <= 0, ">": c > 0, ">=": c >= 0}[op]
        if lhs is None or rhs is None:
            return None
        if op in ("+", "-", "*", "/", "%"):
            return self._arith(op, lhs, rhs)
        if op in ("&", "|", "^"):
            a, b = cast.to_int(lhs, cast.STRICT), cast.to_int(rhs, cast.STRICT)
            return {"&": a & b, "|": a | b, "^": a ^ b}[op]
        raise EvalError(f"unknown binary operator {op}")

    @staticmethod
    def _arith(op: str, lhs: Any, rhs: Any) -> Any:
        if isinstance(lhs, str) or isinstance(rhs, str):
            raise EvalError(
                f"invalid operation string {op} — use concat() for strings"
            )
        both_int = (
            isinstance(lhs, int) and isinstance(rhs, int)
            and not isinstance(lhs, bool) and not isinstance(rhs, bool)
        )
        a = cast.to_float(lhs) if not both_int else lhs
        b = cast.to_float(rhs) if not both_int else rhs
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            if b == 0:
                raise EvalError("division by zero")
            return a // b if both_int else a / b
        if op == "%":
            if b == 0:
                raise EvalError("division by zero")
            return a % b
        raise EvalError(f"unknown arith op {op}")

    def _eval_BetweenExpr(self, e: ast.BetweenExpr, row) -> Any:
        v = self.eval(e.value, row)
        lo = self.eval(e.lo, row)
        hi = self.eval(e.hi, row)
        if v is None or lo is None or hi is None:
            return None
        c_lo = cast.compare(v, lo)
        c_hi = cast.compare(v, hi)
        if c_lo is None or c_hi is None:
            return None  # incomparable types — NULL, like the comparison ops
        result = c_lo >= 0 and c_hi <= 0
        return not result if e.negate else result

    def _eval_InExpr(self, e: ast.InExpr, row) -> Any:
        v = self.eval(e.value, row)
        if v is None:
            return None
        found = False
        for item in e.values:
            iv = self.eval(item, row)
            if iv is not None and cast.compare(v, iv) == 0:
                found = True
                break
            if iv == v:
                found = True
                break
        return not found if e.negate else found

    def _eval_LikeExpr(self, e: ast.LikeExpr, row) -> Any:
        v = self.eval(e.value, row)
        p = self.eval(e.pattern, row)
        if v is None or p is None:
            return None
        # SQL LIKE: % any-run, _ single char; support \ escapes
        regex = _like_to_regex(cast.to_string(p))
        result = regex.fullmatch(cast.to_string(v)) is not None
        return not result if e.negate else result

    def _eval_CaseExpr(self, e: ast.CaseExpr, row) -> Any:
        if e.value is not None:
            v = self.eval(e.value, row)
            for w in e.whens:
                wv = self.eval(w.cond, row)
                if wv is not None and (
                    cast.compare(v, wv) == 0 or v == wv
                ):
                    return self.eval(w.result, row)
        else:
            for w in e.whens:
                if self.eval(w.cond, row) is True:
                    return self.eval(w.result, row)
        if e.else_expr is not None:
            return self.eval(e.else_expr, row)
        return None

    def _eval_IndexExpr(self, e: ast.IndexExpr, row) -> Any:
        v = self.eval(e.value, row)
        if v is None:
            return None
        if e.is_slice:
            lo = self.eval(e.lo, row) if e.lo is not None else None
            hi = self.eval(e.hi, row) if e.hi is not None else None
            if not isinstance(v, (list, tuple, str)):
                raise EvalError("slice on non-array value")
            return v[lo:hi]
        idx = self.eval(e.index, row)
        if isinstance(v, dict):
            return v.get(cast.to_string(idx))
        if isinstance(v, (list, tuple, str)):
            i = cast.to_int(idx)
            if i < -len(v) or i >= len(v):
                raise EvalError(f"index {i} out of range")
            return v[i]
        raise EvalError(f"cannot index {type(v).__name__}")

    def _eval_ArrowExpr(self, e: ast.ArrowExpr, row) -> Any:
        v = self.eval(e.value, row)
        if v is None:
            return None
        if isinstance(v, dict):
            return v.get(e.name)
        raise EvalError(f"arrow access on non-struct {type(v).__name__}")

    # ---------------------------------------------------------------- calls
    def _ctx_for(self, call: ast.Call, row) -> FunctionContext:
        state = self.func_states.setdefault(call.func_id, {})
        return FunctionContext(
            rule_id=self.rule_id,
            func_id=call.func_id,
            state=state,
            window_range=self.window_range,
            row=row,
            keyed_state=self.keyed_state,
            trigger_time=self.trigger_time,
        )

    def _eval_Call(self, e: ast.Call, row) -> Any:
        fd = registry.lookup(e.name)
        if fd is None:
            raise EvalError(f"function {e.name} not found")
        ctx = self._ctx_for(e, row)
        if fd.ftype == registry.AGGREGATE:
            return self._eval_agg_call(e, fd, row, ctx)
        if fd.ftype in (registry.ANALYTIC, registry.WINDOW_FUNC):
            # AnalyticNode/WindowFuncNode pre-compute and cache on the row
            if row is not None:
                cached, ok = row.value(f"__analytic_{e.func_id}")
                if ok:
                    return cached
        if fd.ftype == registry.WINDOW_FUNC:
            args = [self.eval(a, row) for a in e.args]
            return fd.exec(args, ctx)
        if fd.ftype == registry.ANALYTIC:
            partition = ""
            if e.partition:
                partition = "#".join(
                    cast.to_string(self.eval(p, row)) for p in e.partition
                )
            # OVER(WHEN false): peek state, don't update (reference validData=false)
            update = e.when is None or self.eval(e.when, row) is True
            args = [self.eval(a, row) for a in e.args]
            try:
                return fd.exec(args, ctx, partition, update)
            except EvalError:
                raise
            except Exception as ex:
                raise EvalError(f"call {e.name} error: {ex}") from ex
        if e.filter is not None or e.partition:
            raise EvalError(
                f"FILTER/PARTITION BY not supported on scalar function {e.name}"
            )
        if e.when is not None:
            if not fd.stateful:
                raise EvalError(f"OVER(WHEN ...) not supported on {e.name}")
            # stateful scalar (acc_*): WHEN true resets the accumulator state
            if self.eval(e.when, row) is True:
                ctx.state.clear()
        args = [self.eval(a, row) for a in e.args]
        try:
            return fd.exec(args, ctx)
        except EvalError:
            raise
        except Exception as ex:
            raise EvalError(f"call {e.name} error: {ex}") from ex

    def _eval_agg_call(self, e: ast.Call, fd, row, ctx) -> Any:
        """Aggregate call: collect arg values over the group's rows.
        `row` must be a GroupedTuples/Collection; a bare Row means we're in a
        non-grouped agg context (whole collection = the row's group)."""
        pre = getattr(row, "agg_values", None)
        if pre:
            from ..ops.aggspec import _call_key

            key = _call_key(e)
            if key in pre:
                return pre[key]
        rows: List[Row]
        if isinstance(row, GroupedTuples):
            rows = row.rows()
        elif hasattr(row, "rows"):
            rows = row.rows()  # any Collection
        else:
            rows = [row] if row is not None else []
        if e.filter is not None:
            rows = [r for r in rows if self.eval_condition(e.filter, r)]
        arg_lists: List[List[Any]] = []
        for arg in e.args:
            if isinstance(arg, ast.Wildcard):
                arg_lists.append([1] * len(rows))  # count(*)
            else:
                vals = [self.eval(arg, r) for r in rows]
                arg_lists.append(vals)
        if not arg_lists:
            arg_lists = [[1] * len(rows)]
        # first arg: drop nulls for aggregates that skip them is handled in fn
        try:
            return fd.exec(arg_lists, ctx)
        except EvalError:
            raise
        except Exception as ex:
            raise EvalError(f"aggregate {e.name} error: {ex}") from ex


_like_cache: Dict[str, Any] = {}


def _like_to_regex(pattern: str):
    rx = _like_cache.get(pattern)
    if rx is None:
        out = []
        i = 0
        while i < len(pattern):
            c = pattern[i]
            if c == "\\" and i + 1 < len(pattern):
                out.append(re.escape(pattern[i + 1]))
                i += 2
                continue
            if c == "%":
                out.append(".*")
            elif c == "_":
                out.append(".")
            else:
                out.append(re.escape(c))
            i += 1
        rx = re.compile("".join(out), re.DOTALL)
        if len(_like_cache) > 1024:
            _like_cache.clear()
        _like_cache[pattern] = rx
    return rx
