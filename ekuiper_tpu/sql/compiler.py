"""Expression → vectorized batch compiler: the TPU replacement for the
reference's per-row ValuerEval interpreter hot loop (internal/xsql/valuer.go:289).

`compile_expr(expr, mode)` returns a closure evaluating the expression over a
whole ColumnBatch's columns dict at once:

- mode="host": numpy arrays; numeric + boolean ops vectorized on CPU.
- mode="device": jax.numpy — the closure is pure and jit-safe, composed into
  the fused filter→project→window-aggregate kernels (ops/), where XLA fuses
  everything into a few VPU/MXU loops.

Non-vectorizable nodes (string funcs, json path, stateful/analytic calls,
index/arrow access into object columns) raise NotVectorizable at compile
time; the planner then splits the pipeline and routes those expressions
through the row interpreter (sql/eval.py) — the "host fallback" seam the
build plan calls for (SURVEY §7 hard part e).
"""
from __future__ import annotations

import threading
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Set

import numpy as np

from ..functions import registry
from . import ast
from .expr_ir import NotVectorizable  # shared exception (structured reason)

Cols = Dict[str, Any]

# ---------------------------------------------------------- host fallbacks
#: plan-time count of expressions that could not device-compile, by
#: structured NotVectorizable reason — rendered as
#: `kuiper_expr_host_fallback_total{reason}` (docs/OBSERVABILITY.md) so
#: the health plane can name host expression eval instead of binning the
#: cost as "other"
_fallback_lock = threading.Lock()
_host_fallbacks: Counter = Counter()


def record_host_fallback(reason: str) -> None:
    with _fallback_lock:
        _host_fallbacks[reason or "other"] += 1


def host_fallback_counts() -> Dict[str, int]:
    with _fallback_lock:
        return dict(_host_fallbacks)


def reset_host_fallbacks() -> None:
    """Test hook."""
    with _fallback_lock:
        _host_fallbacks.clear()


# device-safe function table: name -> builder(xp, *arg_closures) -> closure
def _u(fname: str):
    """Unary elementwise: xp.<fname>."""

    def build(xp, a):
        fn = getattr(xp, fname)
        return lambda cols: fn(a(cols))

    return build


def _b(fname: str):
    def build(xp, a, b):
        fn = getattr(xp, fname)
        return lambda cols: fn(a(cols), b(cols))

    return build


_DEVICE_FUNCS: Dict[str, Callable] = {
    "abs": _u("abs"),
    "acos": _u("arccos"), "asin": _u("arcsin"), "atan": _u("arctan"),
    "cos": _u("cos"), "cosh": _u("cosh"), "sin": _u("sin"), "sinh": _u("sinh"),
    "tan": _u("tan"), "tanh": _u("tanh"), "exp": _u("exp"), "ln": _u("log"),
    "sqrt": _u("sqrt"), "ceil": _u("ceil"), "ceiling": _u("ceil"),
    "floor": _u("floor"), "round": _u("round"), "sign": _u("sign"),
    "radians": _u("radians"), "degrees": _u("degrees"),
    "atan2": _b("arctan2"), "power": _b("power"), "pow": _b("power"),
    "mod": _b("mod"),
    "bitand": _b("bitwise_and"), "bitor": _b("bitwise_or"),
    "bitxor": _b("bitwise_xor"),
}


def _device_func(name: str, xp, arg_closures):
    if name == "cot":
        a = arg_closures[0]
        return lambda cols: 1.0 / xp.tan(a(cols))
    if name == "bitnot":
        a = arg_closures[0]
        return lambda cols: xp.invert(a(cols))
    if name == "pi":
        return lambda cols: xp.asarray(np.pi, dtype=xp.float32)
    if name == "log":
        if len(arg_closures) == 1:
            a = arg_closures[0]
            return lambda cols: xp.log10(a(cols))
        b_, x_ = arg_closures
        return lambda cols: xp.log(x_(cols)) / xp.log(b_(cols))
    if name == "trunc":
        a, d = arg_closures
        return lambda cols: xp.trunc(a(cols) * 10.0 ** d(cols)) / 10.0 ** d(cols)
    builder = _DEVICE_FUNCS.get(name)
    if builder is None:
        return None
    return builder(xp, *arg_closures)


class Compiler:
    def __init__(self, mode: str = "host", xp=None) -> None:
        self.mode = mode
        if xp is None:
            if mode == "device":
                import jax.numpy as jnp

                xp = jnp
            else:
                xp = np
        self.xp = xp
        self.referenced: Set[str] = set()

    # ---------------------------------------------------------------- compile
    def compile(self, expr: ast.Expr) -> Callable[[Cols], Any]:
        m = getattr(self, "_c_" + type(expr).__name__, None)
        if m is None:
            raise NotVectorizable(type(expr).__name__)
        return m(expr)

    def _c_IntegerLiteral(self, e):
        v = e.val
        return lambda cols: v

    def _c_NumberLiteral(self, e):
        v = e.val
        return lambda cols: v

    def _c_BooleanLiteral(self, e):
        v = e.val
        return lambda cols: v

    def _c_StringLiteral(self, e):
        if self.mode == "device":
            raise NotVectorizable("string literal on device")
        v = e.val
        return lambda cols: v

    def _c_FieldRef(self, e):
        name = e.name
        self.referenced.add(name)

        def get(cols):
            if name not in cols:
                raise NotVectorizable(f"column {name} missing")
            return cols[name]

        return get

    def _c_UnaryExpr(self, e):
        a = self.compile(e.expr)
        xp = self.xp
        if e.op == "-":
            return lambda cols: -a(cols)
        if e.op == "NOT":
            return lambda cols: xp.logical_not(a(cols))
        raise NotVectorizable(f"unary {e.op}")

    _CMP = {
        "=": "equal", "!=": "not_equal", "<": "less", "<=": "less_equal",
        ">": "greater", ">=": "greater_equal",
    }

    def _c_BinaryExpr(self, e):
        a = self.compile(e.lhs)
        b = self.compile(e.rhs)
        xp = self.xp
        op = e.op
        if op in self._CMP:
            fn = getattr(xp, self._CMP[op])
            if self.mode == "host":
                # object columns (strings) compare fine in numpy; guard dtype
                def cmp_host(cols):
                    return fn(a(cols), b(cols))

                return cmp_host
            return lambda cols: fn(a(cols), b(cols))
        if op == "AND":
            return lambda cols: xp.logical_and(a(cols), b(cols))
        if op == "OR":
            return lambda cols: xp.logical_or(a(cols), b(cols))
        if op == "+":
            return lambda cols: a(cols) + b(cols)
        if op == "-":
            return lambda cols: a(cols) - b(cols)
        if op == "*":
            return lambda cols: a(cols) * b(cols)
        if op == "/":
            def div(cols):
                x, y = a(cols), b(cols)
                if _is_int(x) and _is_int(y):
                    return x // y
                return x / y

            return div
        if op == "%":
            return lambda cols: xp.mod(a(cols), b(cols))
        if op in ("&", "|", "^"):
            fn = {
                "&": xp.bitwise_and, "|": xp.bitwise_or, "^": xp.bitwise_xor
            }[op]
            return lambda cols: fn(a(cols), b(cols))
        raise NotVectorizable(f"binary {op}")

    def _c_BetweenExpr(self, e):
        v = self.compile(e.value)
        lo = self.compile(e.lo)
        hi = self.compile(e.hi)
        xp = self.xp
        neg = e.negate

        def run(cols):
            x = v(cols)
            r = xp.logical_and(x >= lo(cols), x <= hi(cols))
            return xp.logical_not(r) if neg else r

        return run

    def _c_InExpr(self, e):
        v = self.compile(e.value)
        items = [self.compile(x) for x in e.values]
        xp = self.xp
        neg = e.negate

        def run(cols):
            x = v(cols)
            r = None
            for item in items:
                eq = x == item(cols)
                r = eq if r is None else xp.logical_or(r, eq)
            if r is None:
                r = xp.zeros(getattr(x, "shape", ()), dtype=bool)
            return xp.logical_not(r) if neg else r

        return run

    def _c_CaseExpr(self, e):
        xp = self.xp
        else_fn = self.compile(e.else_expr) if e.else_expr is not None else None
        # NULL else branch becomes NaN in vectorized numerics
        null = np.nan
        base = self.compile(e.value) if e.value is not None else None
        conds = [(self.compile(w.cond), self.compile(w.result)) for w in e.whens]

        def run(cols):
            out = else_fn(cols) if else_fn is not None else null
            if base is not None:
                x = base(cols)
                for cond, res in reversed(conds):
                    out = xp.where(x == cond(cols), res(cols), out)
            else:
                for cond, res in reversed(conds):
                    out = xp.where(cond(cols), res(cols), out)
            return out

        return run

    def _c_Call(self, e):
        fd = registry.lookup(e.name)
        if fd is None:
            raise NotVectorizable(f"unknown function {e.name}")
        if fd.ftype != registry.SCALAR or fd.stateful:
            raise NotVectorizable(f"{e.name} is not a pure scalar function")
        if e.filter is not None or e.partition or e.when is not None:
            raise NotVectorizable("call clauses")
        args = [self.compile(a) for a in e.args]
        dev = _device_func(e.name, self.xp, args)
        if dev is not None:
            return dev
        if self.mode == "host" and fd.vexec is not None:
            vex = fd.vexec
            return lambda cols: vex(*[a(cols) for a in args])
        raise NotVectorizable(f"no vectorized impl for {e.name}")

    def _c_Wildcard(self, e):
        raise NotVectorizable("wildcard")

    def _c_IndexExpr(self, e):
        raise NotVectorizable("index access")

    def _c_ArrowExpr(self, e):
        raise NotVectorizable("arrow access")

    def _c_LikeExpr(self, e):
        if self.mode == "device":
            raise NotVectorizable("LIKE on device")
        from .eval import _like_to_regex

        v = self.compile(e.value)
        if not isinstance(e.pattern, ast.StringLiteral):
            raise NotVectorizable("dynamic LIKE pattern")
        rx = _like_to_regex(e.pattern.val)
        neg = e.negate

        def run(cols):
            x = v(cols)
            out = np.fromiter(
                (rx.fullmatch(str(s)) is not None for s in x),
                dtype=np.bool_, count=len(x),
            )
            return ~out if neg else out

        return run


class CompiledExpr:
    """Compiled expression + metadata."""

    def __init__(self, fn: Callable[[Cols], Any], columns: Set[str], mode: str) -> None:
        self.fn = fn
        self.columns = columns
        self.mode = mode

    def __call__(self, cols: Cols) -> Any:
        return self.fn(cols)


def compile_expr(expr: ast.Expr, mode: str = "host", xp=None) -> CompiledExpr:
    if mode == "device" and xp is None:
        # device compilation routes through the typed expression IR
        # (sql/expr_ir.py): null-aware closures, CASE/IN/temporal/string
        # operator classes, bounded signature families. The returned
        # CompiledIR is call-compatible with CompiledExpr.
        from .expr_ir import compile_expr_ir

        return compile_expr_ir(expr, mode="device", want="auto")
    c = Compiler(mode=mode, xp=xp)
    fn = c.compile(expr)
    return CompiledExpr(fn, c.referenced, mode)


def try_compile(expr: ast.Expr, mode: str = "host", xp=None) -> Optional[CompiledExpr]:
    try:
        return compile_expr(expr, mode=mode, xp=xp)
    except NotVectorizable:
        return None


def _is_int(x) -> bool:
    dt = getattr(x, "dtype", None)
    if dt is not None:
        return np.issubdtype(dt, np.integer)
    return isinstance(x, int) and not isinstance(x, bool)
