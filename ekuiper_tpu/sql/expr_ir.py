"""Columnar expression IR — device-compiled WHERE / projection / scalar
expressions for the fused filter→project→aggregate kernel (ROADMAP item 4,
in the spirit of TiLT's compiled time-centric query IR, arxiv 2301.12030).

`sql/compiler.py` mode="device" historically rejected every operator class
that was not plain float arithmetic (CASE over strings, temporal
functions, IN, string equality), so whole rules fell back to the host row
interpreter (`sql/eval.py`) — the per-row `NotVectorizable` tax the bench
attributes as host expression eval. This module closes that gap with a
small typed IR:

- **Lowering** (`Lowerer`): ast.Expr → typed IR with a column-type
  inference pass (NUM / STR / TS / BOOL). Types are inferred from usage:
  a column compared against a string literal is a string column; a
  column fed to `hour()`/`year()` (or compared against an epoch-ms-sized
  integer literal) is an int64 event-time column; everything else is
  float32 numeric. Conflicting usage is NotVectorizable, never a guess.
- **Null discipline**: every IR node evaluates to `(value, null_mask)`
  and boolean logic follows the row interpreter's exact semantics
  (`sql/eval.py`): Kleene AND/OR/NOT, `NULL = NULL` true / `NULL = x`
  false, ordered comparisons with NULL are false, arithmetic/BETWEEN/IN
  propagate NULL, and a WHERE that evaluates to NULL drops the row. The
  expression-parity suite (tests/test_expr_ir.py) pins device == host
  twin == row interpreter across these classes.
- **Padding discipline** (jitcert): expressions compile to *bounded*
  signature families. Operand columns keep the kernel's micro-batch
  pad; IN constant vectors pad to a pow-2 ladder (`IN_PAD_LADDER`) with
  a never-matching sentinel; string predicates ride dictionary-encoded
  int32 code columns (`__sd_*`); temporal expressions ride a rebased
  int32 column (`__ts32_*`). The per-column dtype map travels on the
  kernel plan (`KernelPlan.col_dtypes`) into the jitcert fold
  derivations — signature families stay closed.
- **Host prep seam**: string and temporal columns derive on the host
  (vectorized numpy, the same discipline as the `__hll__`/`__hhc__`
  derived columns) via `DerivedCol.encode`; the device kernel only ever
  sees fixed-dtype numeric arrays. Derived columns carry
  self-describing null sentinels (`-1` string code, INT32_MIN ts32) so
  the device closure, the host twin, and the prefinalize host shadow
  agree without extra mask plumbing.

Two symmetric backends come from ONE lowering: `mode="device"` binds the
closures to jax.numpy (pure and jit-safe, composed into
`ops/groupby.py`'s fused fold), `mode="host"` to numpy (the twins the
latency-hiding emit shadows fold with). docs/EXPRESSIONS.md documents
the node set, the padding/bucketing discipline, and the fallback seam.
"""
from __future__ import annotations

import datetime as _dt
import hashlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from . import ast

# ------------------------------------------------------------------ errors


class NotVectorizable(Exception):
    """Expression (or sub-expression) has no vectorized compilation.

    `reason` is a stable slug (the label of
    `kuiper_expr_host_fallback_total` and the `/rules/{id}/explain`
    "expressions" section); the message stays human-oriented.
    """

    def __init__(self, msg: str, reason: str = "other") -> None:
        super().__init__(msg)
        self.reason = reason


# ------------------------------------------------------------- type lattice
NUM = "num"      # float32 device column / python number
STR = "str"      # dictionary-encoded int32 code column
TS = "ts"        # rebased int32 event-time column (epoch ms - anchor)
BOOL = "bool"

#: integer literals at/above this magnitude cannot survive the float32
#: upload (24-bit mantissa) — a bare column compared against one is
#: typed as an int64 event-time column and rides the rebased ts32 path
TS_LITERAL_MIN = 2 ** 31

#: rebased ts32 usable range; values outside become the null sentinel
#: (the device temporal domain is ~±24 days around the plan anchor —
#: docs/EXPRESSIONS.md "temporal domain")
_TS_MAX = 2 ** 31 - 8
TS_NULL = -(2 ** 31)  # int32 min: the ts32 null sentinel

#: string-dict code sentinels: -1 = NULL, -2 = a real value that matches
#: no constant of the dict (never equal to any code >= 0)
SD_NULL = -1
SD_OTHER = -2

#: IN constant vectors pad to the smallest fitting rung of this pow-2
#: ladder — the "bucketed operand shapes" discipline behind jitcert's
#: bounded-signature claim; wider lists fall back to the host row path
IN_PAD_LADDER = (4, 8, 16, 32, 64, 128, 256)

_MS_DAY = 86_400_000

# device-safe elementwise function tables
_MATH_UNARY = {
    "abs": "abs",
    "acos": "arccos", "asin": "arcsin", "atan": "arctan",
    "cos": "cos", "cosh": "cosh", "sin": "sin", "sinh": "sinh",
    "tan": "tan", "tanh": "tanh", "exp": "exp", "ln": "log",
    "sqrt": "sqrt", "ceil": "ceil", "ceiling": "ceil",
    "floor": "floor", "round": "round", "sign": "sign",
    "radians": "radians", "degrees": "degrees",
}
_MATH_BINARY = {
    "atan2": "arctan2", "power": "power", "pow": "power", "mod": "mod",
}

#: temporal extraction functions compiled onto the rebased ts32 column;
#: all exact integer arithmetic (UTC, matching funcs_datetime.py)
TEMPORAL_FUNCS = ("hour", "minute", "second", "day", "day_of_month",
                  "day_of_week", "month", "year")


def plan_anchor_ms() -> int:
    """The plan-time temporal anchor: the engine clock's current UTC
    midnight. All ts32 derivations and rebased literals of one compiled
    expression share it (it is part of the IR key, so prep share keys
    can never mix two anchors)."""
    from ..utils import timex

    return (timex.now_ms() // _MS_DAY) * _MS_DAY


# ------------------------------------------------------------ derived cols
@dataclass(frozen=True)
class DerivedCol:
    """A host-derived device column (the expression-prep seam).

    kind="strdict": `raw` dictionary-encodes against `values` (the
    sorted constants the expression compares it with) into int32 codes:
    index for a match, -2 for any other real value, -1 for NULL.
    kind="ts32":    `raw` (epoch ms, any numeric/object dtype) rebases
    to int32 `raw - anchor`, INT32_MIN for NULL/out-of-range.
    """

    name: str
    raw: str
    kind: str
    values: Tuple[str, ...] = ()
    anchor: int = 0

    @property
    def dtype(self) -> str:
        return "int32"

    def encode(self, col: Optional[np.ndarray], n: int) -> np.ndarray:
        if self.kind == "strdict":
            return self._encode_strdict(col, n)
        return self._encode_ts32(col, n)

    def _encode_strdict(self, col, n: int) -> np.ndarray:
        out = np.full(n, SD_OTHER, dtype=np.int32)
        if col is None:
            out[:] = SD_NULL
            return out
        if col.dtype == np.object_:
            # vectorized: one C-level object-equality sweep per dict
            # constant (dicts are small — the plan's literal set), plus
            # one None sweep. A per-row python loop here was the
            # filter_heavy host-prep bottleneck.
            out[np.equal(col, None)] = SD_NULL
            for i, v in enumerate(self.values):
                out[col == v] = i
            return out
        if np.issubdtype(col.dtype, np.floating):
            out[np.isnan(col)] = SD_NULL
        return out  # numeric column vs string dict: no value ever matches

    def _encode_ts32(self, col, n: int) -> np.ndarray:
        if col is None:
            return np.full(n, TS_NULL, dtype=np.int32)
        if col.dtype == np.object_:
            vals = np.full(n, np.nan, dtype=np.float64)
            # bulk path first: numeric-only object columns convert in C
            try:
                vals = np.asarray(col, dtype=np.float64)
            except (TypeError, ValueError):
                for i, v in enumerate(col.tolist()):
                    if isinstance(v, (int, float)) and \
                            not isinstance(v, bool):
                        vals[i] = float(v)
        else:
            vals = np.asarray(col, dtype=np.float64)
        with np.errstate(invalid="ignore"):
            rel = vals - float(self.anchor)
            bad = ~np.isfinite(rel) | (np.abs(rel) > _TS_MAX)
        rel = np.where(bad, 0.0, rel)
        out = rel.astype(np.int64).astype(np.int32)
        out[bad] = TS_NULL
        return out

    def ir_key(self) -> str:
        if self.kind == "strdict":
            return f"sd({self.raw};{','.join(self.values)})"
        return f"ts32({self.raw};{self.anchor})"


def derived_name(spec_kind: str, raw: str, tag: str) -> str:
    return f"__{spec_kind}_{tag}__{raw}"


def is_derived_expr_col(name: str) -> bool:
    return name.startswith("__sd_") or name.startswith("__ts32_")


def materialize_derived(derived, cols: Dict[str, np.ndarray], sub,
                        expr_tag: str = "") -> None:
    """Fill `cols` with every DerivedCol of `derived` not already built
    (host prep; runs in the fused node's kernel-input build and in the
    shared fold's value-column build). With `expr_tag` the encode rides
    the batch's ("dexpr_host", tag, name) share slot — the SAME key the
    decode pool's pre-upload stage populates (runtime/ingest.py), so a
    prep-enabled pipeline encodes each derived column once per batch,
    not once per consumer."""
    for d in derived:
        if d.name in cols:
            continue
        share = getattr(sub, "share", None) if expr_tag else None
        if share is not None:
            try:
                cols[d.name] = share(
                    ("dexpr_host", expr_tag, d.name),
                    lambda _d=d, _b=sub: _d.encode(
                        _b.columns.get(_d.raw), _b.n))
                continue
            except Exception:
                pass  # share state unavailable: encode directly
        cols[d.name] = d.encode(sub.columns.get(d.raw), sub.n)


# ------------------------------------------------------------- typed value
class _V:
    """A lowered (typed) IR node: canonical key + per-backend builder.

    `build(xp)` returns `fn(cols) -> (value, null)` where `null` is
    None (never null), a bool array, or a python bool scalar; `lit`
    holds the python value for literal nodes (temporal rebasing needs
    to distinguish literals from columns).
    """

    __slots__ = ("ty", "key", "build", "lit")

    def __init__(self, ty: str, key: str,
                 build: Callable[[Any], Callable], lit=None) -> None:
        self.ty = ty
        self.key = key
        self.build = build
        self.lit = lit


def _const(ty: str, key: str, value, lit=None) -> _V:
    return _V(ty, key, lambda xp: lambda cols: (value, None), lit=lit)


def _or_null(xp, a, b):
    if a is None:
        return b
    if b is None:
        return a
    return xp.logical_or(a, b)


def _drop_null(xp, val, n):
    """val AND NOT null — the 'NULL compares false' rule."""
    if n is None:
        return val
    return xp.logical_and(val, xp.logical_not(n))


def _is_floating(v) -> bool:
    dt = getattr(v, "dtype", None)
    if dt is None:
        return isinstance(v, float)
    try:
        return np.issubdtype(np.dtype(str(dt)), np.floating)
    except TypeError:
        return False


def _is_int_like(x) -> bool:
    dt = getattr(x, "dtype", None)
    if dt is not None:
        try:
            return np.issubdtype(np.dtype(str(dt)), np.integer)
        except TypeError:
            return False
    return isinstance(x, int) and not isinstance(x, bool)


# ------------------------------------------------------------ type inference
def _is_ts_literal(e: ast.Expr) -> bool:
    if isinstance(e, ast.IntegerLiteral):
        return abs(e.val) >= TS_LITERAL_MIN
    if isinstance(e, ast.NumberLiteral):
        return abs(e.val) >= TS_LITERAL_MIN and float(e.val).is_integer()
    return False


def _literal_ty(e: ast.Expr) -> Optional[str]:
    if isinstance(e, ast.StringLiteral):
        return STR
    if _is_ts_literal(e):
        return TS
    if isinstance(e, (ast.IntegerLiteral, ast.NumberLiteral)):
        return NUM
    if isinstance(e, ast.BooleanLiteral):
        return BOOL
    return None


def infer_column_types(expr: ast.Expr) -> Dict[str, str]:
    """Usage-driven column typing, iterated to fixpoint. Unification
    groups are comparison/IN/BETWEEN/CASE-match operand sets (a STR or
    TS member types every bare column in the group); temporal function
    arguments force TS; math-function arguments force NUM. Conflicting
    facts raise NotVectorizable("mixed-type-column") — never a guess."""
    types: Dict[str, str] = {}

    def assign(name: str, ty: str) -> bool:
        cur = types.get(name)
        if cur is None:
            types[name] = ty
            return True
        if cur != ty:
            raise NotVectorizable(
                f"column {name} used as both {cur} and {ty}",
                reason="mixed-type-column")
        return False

    def group_ty(exprs: List[ast.Expr]) -> Optional[str]:
        tys = set()
        for e in exprs:
            t = _literal_ty(e)
            if t is None and isinstance(e, ast.FieldRef):
                t = types.get(e.name)
            if t is not None:
                tys.add(t)
        if STR in tys:
            # a STR member only types the group when nothing numeric
            # contradicts it — `a IN (10, 'ok')` must NOT make `a` a
            # string column (the row interpreter just skips the
            # type-mismatched item)
            return STR if not ({NUM, TS} & tys) else None
        if TS in tys:
            return TS
        return None

    def unify(exprs: List[ast.Expr]) -> bool:
        ty = group_ty(exprs)
        if ty not in (STR, TS):
            return False
        changed = False
        for e in exprs:
            if isinstance(e, ast.FieldRef):
                changed |= assign(e.name, ty)
        return changed

    def visit(e: ast.Expr) -> bool:
        changed = False
        if isinstance(e, ast.BinaryExpr) and e.op in (
                "=", "!=", "<", "<=", ">", ">="):
            changed |= unify([e.lhs, e.rhs])
        elif isinstance(e, ast.BinaryExpr) and e.op in ("+", "-"):
            # absolute-time arithmetic: `ts - 1700000000000` types the
            # bare column TS (STR never propagates through arithmetic)
            if group_ty([e.lhs, e.rhs]) == TS:
                changed |= unify([e.lhs, e.rhs])
        elif isinstance(e, ast.InExpr):
            changed |= unify([e.value] + list(e.values))
        elif isinstance(e, ast.BetweenExpr):
            changed |= unify([e.value, e.lo, e.hi])
        elif isinstance(e, ast.CaseExpr) and e.value is not None:
            changed |= unify([e.value] + [w.cond for w in e.whens])
        elif isinstance(e, ast.Call):
            if e.name in TEMPORAL_FUNCS and e.args and \
                    isinstance(e.args[0], ast.FieldRef):
                changed |= assign(e.args[0].name, TS)
            elif e.name in _MATH_UNARY or e.name in _MATH_BINARY or \
                    e.name in ("cot", "bitnot", "log", "trunc"):
                for a in e.args:
                    if isinstance(a, ast.FieldRef):
                        # raises mixed-type-column when the column is
                        # already STR/TS elsewhere — never a guess
                        changed |= assign(a.name, NUM)
        for c in e.children():
            changed |= visit(c)
        return changed

    for _ in range(8):  # fixpoint: type facts only ever narrow
        if not visit(expr):
            break
    return types


# ---------------------------------------------------------------- lowering
class _LowerCtx:
    def __init__(self, types: Dict[str, str], anchor_ms: int,
                 str_seed: Optional[Dict[str, Set[str]]] = None) -> None:
        self.types = types
        self.anchor_ms = int(anchor_ms)
        # raw column -> set of string constants compared with it; the
        # dictionaries finalize (sorted, coded) in compile_expr_ir.
        # `str_seed` pre-populates them with the PLAN-level constant
        # union, so every expression of one plan (WHERE + agg args +
        # FILTERs) derives ONE dictionary column per raw column instead
        # of one per expression — one host encode, one upload.
        self.str_consts: Dict[str, Set[str]] = {
            k: set(v) for k, v in (str_seed or {}).items()}
        self.referenced: Set[str] = set()
        self.sd_names: Dict[str, str] = {}
        self.sd_codes: Dict[str, Dict[str, Any]] = {}
        self.ts_names: Dict[str, str] = {}


class Lowerer:
    """ast.Expr → typed IR closures. One instance per compiled
    expression; the context's string dictionaries and ts32 anchor are
    finalized by compile_expr_ir after the whole tree lowered."""

    def __init__(self, ctx: _LowerCtx) -> None:
        self.ctx = ctx

    # -- dispatch ----------------------------------------------------------
    def lower(self, e: ast.Expr) -> _V:
        m = getattr(self, "_l_" + type(e).__name__, None)
        if m is None:
            raise NotVectorizable(
                type(e).__name__,
                reason=_REASON_BY_NODE.get(type(e).__name__, "other"))
        return m(e)

    # -- literals ----------------------------------------------------------
    def _l_IntegerLiteral(self, e):
        if _is_ts_literal(e):
            rel = self._rebase(e.val)
            return _const(TS, f"ts:{e.val}", rel, lit=e.val)
        return _const(NUM, repr(e.val), e.val, lit=e.val)

    def _l_NumberLiteral(self, e):
        if _is_ts_literal(e):
            rel = self._rebase(int(e.val))
            return _const(TS, f"ts:{int(e.val)}", rel, lit=e.val)
        return _const(NUM, repr(e.val), e.val, lit=e.val)

    def _l_BooleanLiteral(self, e):
        return _const(BOOL, repr(bool(e.val)), bool(e.val), lit=bool(e.val))

    def _l_StringLiteral(self, e):
        # string literals are only meaningful against a dict-encoded
        # column; the enclosing comparison lowers them to codes. A bare
        # string value (projection result, concat operand) has no
        # device representation.
        raise NotVectorizable("bare string value on device",
                              reason="string-value")

    def _rebase(self, ms: int) -> int:
        rel = ms - self.ctx.anchor_ms
        return max(min(rel, _TS_MAX), -_TS_MAX)

    # -- columns -----------------------------------------------------------
    def _l_FieldRef(self, e):
        name = e.name
        self.ctx.referenced.add(name)
        ty = self.ctx.types.get(name, NUM)
        ctx = self.ctx
        if ty == STR:
            ctx.str_consts.setdefault(name, set())

            def build_s(xp, _n=name, _c=ctx):
                def f(cols):
                    v = cols[_c.sd_names[_n]]
                    return v, v == SD_NULL

                return f

            return _V(STR, f"scol:{name}", build_s)
        if ty == TS:
            def build_t(xp, _n=name, _c=ctx):
                def f(cols):
                    v = cols[_c.ts_names[_n]]
                    return v, v == TS_NULL

                return f

            return _V(TS, f"tscol:{name}", build_t)

        def build(xp, _n=name):
            def f(cols):
                if _n not in cols:
                    raise NotVectorizable(f"column {_n} missing",
                                          reason="missing-column")
                v = cols[_n]
                null = xp.isnan(v) if _is_floating(v) else None
                vm = cols.get("__valid_" + _n)
                if vm is not None:
                    null = _or_null(xp, null, xp.logical_not(vm))
                return v, null

            return f

        return _V(NUM, f"col:{name}", build)

    # -- unary -------------------------------------------------------------
    def _l_UnaryExpr(self, e):
        a = self.lower(e.expr)
        if e.op == "-":
            if a.ty != NUM:
                raise NotVectorizable(f"unary - on {a.ty}",
                                      reason="type-mismatch")

            def build_n(xp, _a=a):
                fa = _a.build(xp)

                def f(cols):
                    v, n = fa(cols)
                    return -v, n

                return f

            return _V(NUM, f"(-{a.key})", build_n)
        if e.op == "NOT":
            if a.ty != BOOL:
                raise NotVectorizable("NOT on non-boolean",
                                      reason="type-mismatch")

            def build(xp, _a=a):
                fa = _a.build(xp)

                def f(cols):
                    v, n = fa(cols)
                    return xp.logical_not(v), n  # Kleene: NOT NULL = NULL

                return f

            return _V(BOOL, f"(NOT {a.key})", build)
        raise NotVectorizable(f"unary {e.op}", reason="operator")

    # -- AND / OR ----------------------------------------------------------
    def _logic(self, e):
        a, b = self.lower(e.lhs), self.lower(e.rhs)
        for s in (a, b):
            if s.ty != BOOL:
                raise NotVectorizable(f"{e.op} on non-boolean {s.ty}",
                                      reason="type-mismatch")
        is_and = e.op == "AND"

        def build(xp, _a=a, _b=b, _and=is_and):
            fa, fb = _a.build(xp), _b.build(xp)

            def f(cols):
                av, an = fa(cols)
                bv, bn = fb(cols)
                at = _drop_null(xp, av, an)       # definitely true
                bt = _drop_null(xp, bv, bn)
                either = _or_null(xp, an, bn)
                if _and:
                    val = xp.logical_and(at, bt)
                    if either is None:
                        return val, None
                    # false wins over null: null only where neither side
                    # is definitely false
                    af = _drop_null(xp, xp.logical_not(av), an)
                    bf = _drop_null(xp, xp.logical_not(bv), bn)
                    null = xp.logical_and(
                        either,
                        xp.logical_not(xp.logical_or(af, bf)))
                    return val, null
                val = xp.logical_or(at, bt)
                if either is None:
                    return val, None
                # true wins over null
                null = xp.logical_and(either, xp.logical_not(val))
                return val, null

            return f

        return _V(BOOL, f"({a.key} {e.op} {b.key})", build)

    # -- comparisons -------------------------------------------------------
    _CMP = {"=": "equal", "!=": "not_equal", "<": "less",
            "<=": "less_equal", ">": "greater", ">=": "greater_equal"}

    def _l_BinaryExpr(self, e):
        if e.op in ("AND", "OR"):
            return self._logic(e)
        if e.op in self._CMP:
            return self._cmp(e.op, e.lhs, e.rhs)
        return self._arith(e)

    def _str_code(self, raw: str, value: str) -> _V:
        """A string literal resolved against `raw`'s dictionary (codes
        finalize after lowering; the closure reads them at call time)."""
        self.ctx.str_consts.setdefault(raw, set()).add(value)

        def build(xp, _raw=raw, _v=value, _c=self.ctx):
            def f(cols):
                return _c.sd_codes[_raw][_v], None

            return f

        return _V(STR, f"str:{value!r}", build, lit=value)

    def _ts_coerced(self, v: _V) -> _V:
        """A NUM literal used where the other side is temporal: the
        literal is an ABSOLUTE epoch-ms time — rebase it (durations
        appear under arithmetic, which does not coerce)."""
        rel = self._rebase(int(v.lit))
        return _const(TS, f"ts:{int(v.lit)}", rel, lit=v.lit)

    def _cmp(self, op: str, lhs_e: ast.Expr, rhs_e: ast.Expr) -> _V:
        l_str = isinstance(lhs_e, ast.StringLiteral)
        r_str = isinstance(rhs_e, ast.StringLiteral)
        if l_str and r_str:
            if op in ("=", "!="):
                eq = (lhs_e.val == rhs_e.val) == (op == "=")
                return _const(BOOL, f"{lhs_e.val!r}{op}{rhs_e.val!r}", eq)
            raise NotVectorizable("ordered comparison of string literals",
                                  reason="string-order-compare")
        if l_str or r_str:
            lit = lhs_e if l_str else rhs_e
            other = self.lower(rhs_e if l_str else lhs_e)
            if other.ty != STR:
                return self._cmp_mismatch(op, other, None)
            if op not in ("=", "!="):
                raise NotVectorizable(
                    "ordered comparison on dictionary-encoded strings",
                    reason="string-order-compare")
            raw = other.key.split(":", 1)[1]
            code = self._str_code(raw, lit.val)
            a, b = (code, other) if l_str else (other, code)
            return self._cmp_plain(op, a, b)
        a, b = self.lower(lhs_e), self.lower(rhs_e)
        # temporal coercion: a NUM literal against a TS side is an
        # absolute time
        if a.ty == TS and b.ty == NUM and b.lit is not None:
            b = self._ts_coerced(b)
        elif b.ty == TS and a.ty == NUM and a.lit is not None:
            a = self._ts_coerced(a)
        if a.ty == STR and b.ty == STR:
            raise NotVectorizable(
                "string column vs string column comparison",
                reason="string-col-compare")
        if {a.ty, b.ty} in ({NUM, STR}, {TS, STR}, {NUM, TS}):
            return self._cmp_mismatch(op, a, b)
        if BOOL in (a.ty, b.ty) and a.ty != b.ty:
            return self._cmp_mismatch(op, a, b)
        if a.ty == STR and op not in ("=", "!="):
            raise NotVectorizable(
                "ordered comparison on dictionary-encoded strings",
                reason="string-order-compare")
        return self._cmp_plain(op, a, b)

    def _cmp_plain(self, op: str, a: _V, b: _V) -> _V:
        fn_name = self._CMP[op]

        def build(xp, _a=a, _b=b, _op=op, _fn=fn_name):
            fa, fb = _a.build(xp), _b.build(xp)
            cmp_fn = getattr(xp, _fn)

            def f(cols):
                av, an = fa(cols)
                bv, bn = fb(cols)
                either = _or_null(xp, an, bn)
                raw = cmp_fn(av, bv)
                if _op not in ("=", "!="):
                    # NULL orders false (sql/eval.py cast.compare)
                    return _drop_null(xp, raw, either), None
                if either is None:
                    return raw, None
                both = (xp.logical_and(an, bn)
                        if an is not None and bn is not None else False)
                eq = _drop_null(xp, raw, either)
                if both is not False:
                    eq = xp.logical_or(eq, both)      # NULL = NULL is true
                if _op == "=":
                    return eq, None
                one = (xp.logical_and(either, xp.logical_not(both))
                       if both is not False else either)
                neq = _drop_null(xp, raw, either)
                return xp.logical_or(neq, one), None  # NULL != x is true

            return f

        return _V(BOOL, f"({a.key}{op}{b.key})", build)

    def _cmp_mismatch(self, op: str, a: _V, b: Optional[_V]) -> _V:
        """Type-mismatched comparison, reference semantics: '=' is true
        only when BOTH sides are NULL, '!=' is its negation, ordered
        comparisons are false (sql/eval.py: cast.compare -> None)."""
        if op not in ("=", "!="):
            key = f"(mismatch {op} {a.key})"
            return _const(BOOL, key, False)
        sides = [s for s in (a, b) if s is not None]

        def build(xp, _sides=tuple(sides), _op=op):
            fns = [s.build(xp) for s in _sides]
            n_sides = len(_sides)

            def f(cols):
                nulls = [fn(cols)[1] for fn in fns]
                if n_sides < 2 or any(n is None for n in nulls):
                    both = False  # a literal side is never null
                else:
                    both = xp.logical_and(nulls[0], nulls[1])
                if _op == "=":
                    return both, None
                return (xp.logical_not(both)
                        if both is not False else True), None

            return f

        keys = "/".join(s.key for s in sides)
        return _V(BOOL, f"(mismatch {op} {keys})", build)

    # -- arithmetic --------------------------------------------------------
    def _arith(self, e):
        a, b = self.lower(e.lhs), self.lower(e.rhs)
        op = e.op
        if BOOL in (a.ty, b.ty) or STR in (a.ty, b.ty):
            raise NotVectorizable(f"arithmetic {op} on {a.ty}/{b.ty}",
                                  reason="type-mismatch")
        out_ty = NUM
        if TS in (a.ty, b.ty):
            if op not in ("+", "-"):
                raise NotVectorizable(
                    f"temporal arithmetic only supports +/- (got {op})",
                    reason="temporal-arith")
            if a.ty == TS and b.ty == TS:
                if op == "+":
                    raise NotVectorizable("adding two timestamps",
                                          reason="temporal-arith")
                out_ty = NUM  # ts - ts = duration ms (int32 exact)
            else:
                other = b if a.ty == TS else a
                if other.lit is None:
                    # dynamic float deltas would round through float32
                    raise NotVectorizable(
                        "temporal ± dynamic operand (literal offsets "
                        "only)", reason="temporal-arith")
                out_ty = TS

        def build(xp, _a=a, _b=b, _op=op):
            fa, fb = _a.build(xp), _b.build(xp)

            def f(cols):
                av, an = fa(cols)
                bv, bn = fb(cols)
                null = _or_null(xp, an, bn)
                if _op == "+":
                    v = av + bv
                elif _op == "-":
                    v = av - bv
                elif _op == "*":
                    v = av * bv
                elif _op == "/":
                    if _is_int_like(av) and _is_int_like(bv):
                        v = av // bv
                    else:
                        v = av / bv
                elif _op == "%":
                    v = xp.mod(av, bv)
                else:
                    fn = {"&": xp.bitwise_and, "|": xp.bitwise_or,
                          "^": xp.bitwise_xor}[_op]
                    v = fn(_as_int(xp, av), _as_int(xp, bv))
                return v, null

            return f

        return _V(out_ty, f"({a.key}{op}{b.key})", build)

    # -- BETWEEN / IN ------------------------------------------------------
    def _l_BetweenExpr(self, e):
        v = self.lower(e.value)
        lo = self.lower(e.lo)
        hi = self.lower(e.hi)
        if v.ty == TS:
            if lo.ty == NUM and lo.lit is not None:
                lo = self._ts_coerced(lo)
            if hi.ty == NUM and hi.lit is not None:
                hi = self._ts_coerced(hi)
        for s in (v, lo, hi):
            if s.ty not in (NUM, TS):
                raise NotVectorizable("BETWEEN on non-numeric",
                                      reason="type-mismatch")
        neg = bool(e.negate)

        def build(xp, _v=v, _lo=lo, _hi=hi, _neg=neg):
            fv, fl, fh = _v.build(xp), _lo.build(xp), _hi.build(xp)

            def f(cols):
                vv, vn = fv(cols)
                lv, ln = fl(cols)
                hv, hn = fh(cols)
                null = _or_null(xp, _or_null(xp, vn, ln), hn)
                raw = xp.logical_and(vv >= lv, vv <= hv)
                if _neg:
                    raw = xp.logical_not(raw)
                return _drop_null(xp, raw, null), null

            return f

        tag = "NOT BETWEEN" if neg else "BETWEEN"
        return _V(BOOL, f"({v.key} {tag} {lo.key},{hi.key})", build)

    def _l_InExpr(self, e):
        v = self.lower(e.value)
        all_literal = all(_literal_ty(x) is not None for x in e.values)
        if not all_literal:
            return self._in_dynamic(e, v)
        if len(e.values) > IN_PAD_LADDER[-1]:
            raise NotVectorizable(
                f"IN list wider than the {IN_PAD_LADDER[-1]} pad cap",
                reason="in-too-wide")
        neg = bool(e.negate)
        if v.ty == STR:
            raw = v.key.split(":", 1)[1]
            values = sorted({x.val for x in e.values
                             if isinstance(x, ast.StringLiteral)})
            for s in values:
                self.ctx.str_consts.setdefault(raw, set()).add(s)

            def build_s(xp, _v=v, _raw=raw, _vals=tuple(values),
                        _neg=neg, _c=self.ctx):
                fv = _v.build(xp)

                def f(cols):
                    codes = [int(_c.sd_codes[_raw][s]) for s in _vals]
                    consts = _pad_consts(codes, SD_OTHER - 1, np.int32)
                    vv, vn = fv(cols)
                    hit = xp.any(
                        xp.expand_dims(vv, -1) == xp.asarray(consts), -1)
                    if _neg:
                        hit = xp.logical_not(hit)
                    return _drop_null(xp, hit, vn), vn

                return f

            tag = "NOT IN" if neg else "IN"
            return _V(BOOL, f"({v.key} {tag} s[{','.join(values)}])",
                      build_s)
        # numeric / temporal operand: only numeric constants can match
        # (string items compare None in the row interpreter — skipped)
        consts: List[float] = [
            float(x.val) for x in e.values
            if isinstance(x, (ast.IntegerLiteral, ast.NumberLiteral,
                              ast.BooleanLiteral))]
        if v.ty == TS:
            padded = _pad_consts([self._rebase(int(c)) for c in consts],
                                 TS_NULL + 1, np.int32)
        else:
            padded = _pad_consts(consts, np.nan, np.float32)

        def build(xp, _v=v, _c=padded, _neg=neg):
            fv = _v.build(xp)

            def f(cols):
                vv, vn = fv(cols)
                hit = xp.any(xp.expand_dims(vv, -1) == xp.asarray(_c), -1)
                if _neg:
                    hit = xp.logical_not(hit)
                return _drop_null(xp, hit, vn), vn

            return f

        tag = "NOT IN" if neg else "IN"
        return _V(BOOL, f"({v.key} {tag} {padded.tolist()})", build)

    def _in_dynamic(self, e, v: _V) -> _V:
        """IN with non-literal items: OR-chain of equalities, with the
        IN null rule (a NULL operand is NULL regardless of the items)."""
        items = [self._cmp("=", e.value, x) for x in e.values]
        neg = bool(e.negate)

        def build(xp, _v=v, _items=tuple(items), _neg=neg):
            fv = _v.build(xp)
            fns = [i.build(xp) for i in _items]

            def f(cols):
                _, vn = fv(cols)
                hit = False
                for fn in fns:
                    iv, _ = fn(cols)
                    hit = iv if hit is False else xp.logical_or(hit, iv)
                if _neg:
                    hit = xp.logical_not(hit)
                return _drop_null(xp, hit, vn), vn

            return f

        tag = "NOT IN" if neg else "IN"
        return _V(BOOL, f"({v.key} {tag} dyn[{len(items)}])", build)

    # -- CASE --------------------------------------------------------------
    def _l_CaseExpr(self, e):
        if e.value is not None:
            whens = [(self._cmp("=", e.value, w.cond),
                      self.lower(w.result)) for w in e.whens]
        else:
            whens = [(self.lower(w.cond), self.lower(w.result))
                     for w in e.whens]
        for cond, res in whens:
            if cond.ty != BOOL:
                raise NotVectorizable("CASE condition is not boolean",
                                      reason="type-mismatch")
            if res.ty != NUM:
                # TS results are anchor-rebased int32 — letting them out
                # as a NUM would silently emit epoch-ms-minus-anchor
                raise NotVectorizable(
                    f"CASE result of type {res.ty} on device",
                    reason="string-value" if res.ty == STR
                    else "temporal-value")
        els = self.lower(e.else_expr) if e.else_expr is not None else None
        if els is not None and els.ty != NUM:
            raise NotVectorizable("CASE else of unsupported type",
                                  reason="temporal-value"
                                  if els.ty == TS else "type-mismatch")

        def build(xp, _whens=tuple(whens), _els=els):
            fws = [(c.build(xp), r.build(xp)) for c, r in _whens]
            fe = _els.build(xp) if _els is not None else None

            def f(cols):
                if fe is not None:
                    val, null = fe(cols)
                    null = False if null is None else null
                else:
                    val, null = np.float32(np.nan), True
                for fc, fr in reversed(fws):
                    cv, cn = fc(cols)
                    take = _drop_null(xp, cv, cn)
                    rv, rn = fr(cols)
                    val = xp.where(take, rv, val)
                    null = xp.where(take, False if rn is None else rn,
                                    null)
                if null is False:
                    null = None
                return val, null

            return f

        key = "CASE(" + ";".join(f"{c.key}->{r.key}" for c, r in whens) \
            + (f";else {els.key}" if els is not None else "") + ")"
        return _V(NUM, key, build)

    # -- calls -------------------------------------------------------------
    def _l_Call(self, e):
        if e.filter is not None or e.partition or e.when is not None:
            raise NotVectorizable("call clauses", reason="call-clause")
        if e.name in TEMPORAL_FUNCS:
            return self._temporal_call(e)
        if e.name == "pi":
            return _const(NUM, "pi", float(np.pi))
        args = [self.lower(a) for a in e.args]
        for a in args:
            if a.ty != NUM:
                raise NotVectorizable(f"{e.name} argument of type {a.ty}",
                                      reason="type-mismatch")
        builder = self._math_builder(e.name, len(args))
        if builder is None:
            from ..functions import registry

            fd = registry.lookup(e.name)
            if fd is None:
                raise NotVectorizable(f"unknown function {e.name}",
                                      reason="unknown-func")
            reason = ("stateful-func" if getattr(fd, "stateful", False)
                      or fd.ftype != registry.SCALAR
                      else "unvectorized-func")
            raise NotVectorizable(f"no device impl for {e.name}",
                                  reason=reason)

        def build(xp, _args=tuple(args), _b=builder):
            fns = [a.build(xp) for a in _args]
            impl = _b(xp)

            def f(cols):
                pairs = [fn(cols) for fn in fns]
                null = None
                for _, n in pairs:
                    null = _or_null(xp, null, n)
                return impl(*[v for v, _ in pairs]), null

            return f

        key = f"{e.name}({','.join(a.key for a in args)})"
        return _V(NUM, key, build)

    @staticmethod
    def _math_builder(name: str, arity: int):
        if name in _MATH_UNARY and arity == 1:
            fname = _MATH_UNARY[name]
            return lambda xp: getattr(xp, fname)
        if name in _MATH_BINARY and arity == 2:
            fname = _MATH_BINARY[name]
            return lambda xp: getattr(xp, fname)
        if name in ("bitand", "bitor", "bitxor") and arity == 2:
            fname = {"bitand": "bitwise_and", "bitor": "bitwise_or",
                     "bitxor": "bitwise_xor"}[name]
            return lambda xp: (lambda a, b: getattr(xp, fname)(
                _as_int(xp, a), _as_int(xp, b)))
        if name == "cot" and arity == 1:
            return lambda xp: (lambda a: 1.0 / xp.tan(a))
        if name == "bitnot" and arity == 1:
            return lambda xp: (lambda a: xp.invert(_as_int(xp, a)))
        if name == "log":
            if arity == 1:
                return lambda xp: xp.log10
            if arity == 2:
                return lambda xp: (lambda b, x: xp.log(x) / xp.log(b))
        if name == "trunc" and arity == 2:
            return lambda xp: (
                lambda a, d: xp.trunc(a * 10.0 ** d) / 10.0 ** d)
        return None

    def _temporal_call(self, e):
        if len(e.args) != 1:
            raise NotVectorizable(f"{e.name} arity", reason="temporal-func")
        a = self.lower(e.args[0])
        if a.ty != TS:
            raise NotVectorizable(f"{e.name} on a non-temporal operand",
                                  reason="temporal-func")
        anchor = self.ctx.anchor_ms
        anchor_days = anchor // _MS_DAY
        anchor_wd = _dt.datetime.fromtimestamp(
            anchor / 1000.0, tz=_dt.timezone.utc).weekday()  # Mon=0
        name = e.name

        def build(xp, _a=a, _name=name, _days=anchor_days, _wd=anchor_wd):
            fa = _a.build(xp)

            def f(cols):
                v, n = fa(cols)
                # the anchor is UTC-midnight-aligned, so v mod day ==
                # ts mod day; floor-mod keeps pre-anchor rows exact
                if _name == "hour":
                    out = (v % _MS_DAY) // 3_600_000
                elif _name == "minute":
                    out = (v % 3_600_000) // 60_000
                elif _name == "second":
                    out = (v % 60_000) // 1000
                elif _name == "day_of_week":
                    days = v // _MS_DAY
                    # reference: Sunday=1 .. Saturday=7 (funcs_datetime)
                    out = ((_wd + days) % 7 + 1) % 7 + 1
                else:
                    y, m, d = _civil(xp, v // _MS_DAY + _days)
                    out = {"year": y, "month": m, "day": d,
                           "day_of_month": d}[_name]
                return out, n

            return f

        return _V(NUM, f"{name}({a.key})", build)

    # -- unsupported node classes (structured reasons) ---------------------
    def _l_LikeExpr(self, e):
        raise NotVectorizable("LIKE on device", reason="like")

    def _l_Wildcard(self, e):
        raise NotVectorizable("wildcard", reason="wildcard")

    def _l_IndexExpr(self, e):
        raise NotVectorizable("index access", reason="json-path")

    def _l_ArrowExpr(self, e):
        raise NotVectorizable("arrow access", reason="json-path")

    def _l_MetaRef(self, e):
        raise NotVectorizable("meta reference", reason="meta-ref")


_REASON_BY_NODE = {
    "LikeExpr": "like", "IndexExpr": "json-path", "ArrowExpr": "json-path",
    "Wildcard": "wildcard", "MetaRef": "meta-ref",
}


def _pad_consts(values, pad_val, dtype) -> np.ndarray:
    """Pad an IN constant list to the pow-2 ladder with a sentinel that
    can never match a real operand value (bucketed operand shapes)."""
    n = max(len(values), 1)
    b = IN_PAD_LADDER[-1]
    for b in IN_PAD_LADDER:
        if b >= n:
            break
    out = np.full(b, pad_val, dtype=dtype)
    if values:
        out[:len(values)] = np.asarray(values, dtype=dtype)
    return out


def _as_int(xp, v):
    if _is_int_like(v):
        return v
    if hasattr(v, "dtype") or hasattr(v, "aval"):
        return xp.asarray(v).astype(np.int32)
    return int(v)


def _civil(xp, z):
    """Days-since-epoch → (year, month, day): Howard Hinnant's civil
    algorithm in pure int32 ops."""
    z = z + 719_468
    era = z // 146_097
    doe = z - era * 146_097
    yoe = (doe - doe // 1460 + doe // 36_524 - doe // 146_096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + xp.where(mp < 10, 3, -9)
    return y + (m <= 2), m, d


# --------------------------------------------------------------- compiled
class CompiledIR:
    """One compiled expression: a backend closure plus the plan facts
    the kernel integration needs (device columns, dtypes, derived-column
    prep, canonical IR key). Call-compatible with
    sql/compiler.CompiledExpr (fn/columns/mode/__call__)."""

    def __init__(self, fn, columns: Set[str], mode: str, *,
                 raw_columns: Set[str], col_dtypes: Dict[str, str],
                 derived: Tuple[DerivedCol, ...], ir_key: str,
                 ty: str) -> None:
        self.fn = fn
        self.columns = columns
        self.mode = mode
        self.raw_columns = raw_columns
        self.col_dtypes = col_dtypes
        self.derived = derived
        self.ir_key = ir_key
        self.ty = ty

    def __call__(self, cols) -> Any:
        return self.fn(cols)


def ir_hash(keys) -> str:
    h = hashlib.sha1()
    for k in sorted(keys):
        h.update(k.encode("utf-8"))
        h.update(b"\x00")
    return h.hexdigest()[:10]


def collect_str_consts(expr: ast.Expr) -> Dict[str, Set[str]]:
    """Plan-level pre-pass: (string column -> string constants) pairs an
    expression would build dictionaries from — union these across every
    expression of a plan and seed compile_expr_ir with the result, so
    the whole plan derives ONE `__sd_*` column per raw column."""
    try:
        types = infer_column_types(expr)
    except NotVectorizable:
        return {}
    out: Dict[str, Set[str]] = {}

    def note(col_e, lit_es) -> None:
        if not isinstance(col_e, ast.FieldRef) or \
                types.get(col_e.name) != STR:
            return
        vals = {x.val for x in lit_es if isinstance(x, ast.StringLiteral)}
        if vals:
            out.setdefault(col_e.name, set()).update(vals)

    for node in ast.walk(expr):
        if isinstance(node, ast.BinaryExpr) and node.op in ("=", "!="):
            note(node.lhs, [node.rhs])
            note(node.rhs, [node.lhs])
        elif isinstance(node, ast.InExpr):
            note(node.value, node.values)
        elif isinstance(node, ast.CaseExpr) and node.value is not None:
            note(node.value, [w.cond for w in node.whens])
    return out


def compile_expr_ir(expr: ast.Expr, mode: str = "device",
                    want: str = "auto",
                    anchor_ms: Optional[int] = None,
                    str_seed: Optional[Dict[str, Set[str]]] = None
                    ) -> CompiledIR:
    """Lower + compile one expression for `mode` ("device" → jax.numpy,
    "host" → the numpy twin). `want`:
      "bool"   — a WHERE/FILTER mask: NULL and non-boolean drop the row
                 (sql/eval.py eval_condition's `v is True`).
      "number" — a float32 value column with NaN at NULLs (agg args).
      "auto"   — the node's own value (bool: NULL→False; num: NULL→NaN).
    Raises NotVectorizable (with a structured `reason`) when any node
    has no device form.
    """
    types = infer_column_types(expr)
    ctx = _LowerCtx(types, plan_anchor_ms() if anchor_ms is None
                    else int(anchor_ms), str_seed=str_seed)
    root = Lowerer(ctx).lower(expr)
    # finalize string dictionaries: codes index the SORTED constant
    # tuple, so the same (column, constant-set) pair always derives the
    # same column name and codes across rules — shared folds dedup them
    derived: List[DerivedCol] = []
    for raw, consts in sorted(ctx.str_consts.items()):
        if types.get(raw) != STR or raw not in ctx.referenced:
            continue  # seeded column this expression never reads
        values = tuple(sorted(consts))
        name = derived_name(
            "sd", raw, ir_hash([f"{raw}|{v}" for v in values])[:8])
        ctx.sd_names[raw] = name
        ctx.sd_codes[raw] = {v: np.int32(i) for i, v in enumerate(values)}
        derived.append(DerivedCol(name=name, raw=raw, kind="strdict",
                                  values=values))
    for raw, ty in sorted(types.items()):
        if ty != TS or raw not in ctx.referenced:
            continue
        name = derived_name(
            "ts32", raw, ir_hash([f"{raw}|{ctx.anchor_ms}"])[:8])
        ctx.ts_names[raw] = name
        derived.append(DerivedCol(name=name, raw=raw, kind="ts32",
                                  anchor=ctx.anchor_ms))
    if mode == "device":
        import jax.numpy as jnp

        xp = jnp
    else:
        xp = np
    inner = root.build(xp)
    ty = root.ty

    if want != "bool" and ty == TS:
        # a raw temporal VALUE has no device representation outside
        # comparisons/temporal functions: the rebased int32 would leak
        # out as epoch-ms-minus-anchor. (ts − ts durations are NUM and
        # pass; aggregates over a bare ts column type it NUM and take
        # the ordinary float path.)
        raise NotVectorizable("temporal value consumed as a number",
                              reason="temporal-value")
    if want == "bool":
        if ty != BOOL:
            # a non-boolean WHERE never equals True in the row
            # interpreter — every row drops; keep that exact contract
            def fn(cols):
                return False
        else:
            def fn(cols):
                v, n = inner(cols)
                return _drop_null(xp, v, n)
    elif want == "number":
        if ty == BOOL:
            def fn(cols):
                v, n = inner(cols)
                out = xp.where(v, np.float32(1.0), np.float32(0.0))
                if n is not None:
                    out = xp.where(n, np.float32(np.nan), out)
                return out
        else:
            def fn(cols):
                v, n = inner(cols)
                if hasattr(v, "dtype") or hasattr(v, "aval"):
                    v = xp.asarray(v).astype(np.float32)
                if n is not None:
                    v = xp.where(n, np.float32(np.nan), v)
                return v
    else:
        def fn(cols):
            v, n = inner(cols)
            if n is None:
                return v
            if ty == BOOL:
                return _drop_null(xp, v, n)
            return xp.where(n, np.float32(np.nan), v)

    col_dtypes: Dict[str, str] = {}
    columns: Set[str] = set()
    dmap = {d.raw: d for d in derived}
    for name in ctx.referenced:
        d = dmap.get(name)
        if d is not None:
            columns.add(d.name)
            col_dtypes[d.name] = d.dtype
        else:
            columns.add(name)
            col_dtypes[name] = "float32"
    key = f"{root.key}|want={want}"
    if any(d.kind == "ts32" for d in derived):
        key += f"|anchor={ctx.anchor_ms}"
    return CompiledIR(fn, columns, mode, raw_columns=set(ctx.referenced),
                      col_dtypes=col_dtypes, derived=tuple(derived),
                      ir_key=key, ty=ty)


def try_compile_ir(expr: ast.Expr, mode: str = "device",
                   want: str = "auto",
                   anchor_ms: Optional[int] = None,
                   str_seed: Optional[Dict[str, Set[str]]] = None
                   ) -> Optional[CompiledIR]:
    try:
        return compile_expr_ir(expr, mode=mode, want=want,
                               anchor_ms=anchor_ms, str_seed=str_seed)
    except NotVectorizable:
        return None
