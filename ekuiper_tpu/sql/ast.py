"""SQL AST — analogue of eKuiper's pkg/ast (statement.go, expr.go).

Node shapes mirror the reference semantically (window types and their
Length/Interval/Delay/TimeUnit fields match pkg/ast/statement.go:183-230;
operator precedence matches pkg/ast/token.go:303-318) so rule definitions
written for the reference parse to the same meaning here.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Iterator, List, Optional

from ..data.types import DataType


# ---------------------------------------------------------------- expressions
class Expr:
    def children(self) -> List["Expr"]:
        return []


@dataclass
class IntegerLiteral(Expr):
    val: int


@dataclass
class NumberLiteral(Expr):
    val: float


@dataclass
class StringLiteral(Expr):
    val: str


@dataclass
class BooleanLiteral(Expr):
    val: bool


@dataclass
class TimeLiteral(Expr):
    """Window time-unit token: DD/HH/MI/SS/MS."""

    val: str


@dataclass
class Wildcard(Expr):
    """`*` — optionally qualified (stream.*) or with eKuiper's
    EXCEPT(...)/REPLACE(...) modifiers."""

    stream: str = ""
    except_names: List[str] = field(default_factory=list)
    replaces: List["Field"] = field(default_factory=list)


@dataclass
class FieldRef(Expr):
    """Column reference, optionally qualified: `stream.name` or `name`."""

    name: str
    stream: str = ""


@dataclass
class MetaRef(Expr):
    """meta(key) / mqtt(topic) style metadata reference."""

    name: str
    stream: str = ""


@dataclass
class BinaryExpr(Expr):
    op: str  # one of OPERATORS below
    lhs: Expr
    rhs: Expr

    def children(self) -> List[Expr]:
        return [self.lhs, self.rhs]


@dataclass
class UnaryExpr(Expr):
    op: str  # '-' | 'NOT'
    expr: Expr

    def children(self) -> List[Expr]:
        return [self.expr]


@dataclass
class BetweenExpr(Expr):
    value: Expr
    lo: Expr
    hi: Expr
    negate: bool = False

    def children(self) -> List[Expr]:
        return [self.value, self.lo, self.hi]


@dataclass
class InExpr(Expr):
    value: Expr
    values: List[Expr]
    negate: bool = False

    def children(self) -> List[Expr]:
        return [self.value] + list(self.values)


@dataclass
class LikeExpr(Expr):
    value: Expr
    pattern: Expr
    negate: bool = False

    def children(self) -> List[Expr]:
        return [self.value, self.pattern]


@dataclass
class CaseExpr(Expr):
    """CASE [value] WHEN cond THEN res ... [ELSE default] END."""

    value: Optional[Expr]
    whens: List["WhenClause"] = field(default_factory=list)
    else_expr: Optional[Expr] = None

    def children(self) -> List[Expr]:
        out: List[Expr] = []
        if self.value is not None:
            out.append(self.value)
        for w in self.whens:
            out.extend([w.cond, w.result])
        if self.else_expr is not None:
            out.append(self.else_expr)
        return out


@dataclass
class WhenClause:
    cond: Expr
    result: Expr


@dataclass
class IndexExpr(Expr):
    """`a[i]` element access or `a[lo:hi]` slice (json path ops)."""

    value: Expr
    index: Optional[Expr] = None
    lo: Optional[Expr] = None
    hi: Optional[Expr] = None
    is_slice: bool = False

    def children(self) -> List[Expr]:
        return [c for c in (self.value, self.index, self.lo, self.hi) if c is not None]


@dataclass
class ArrowExpr(Expr):
    """`a->b` nested struct field access."""

    value: Expr
    name: str

    def children(self) -> List[Expr]:
        return [self.value]


@dataclass
class Call(Expr):
    """Function call. `func_id` distinguishes multiple instances of a stateful
    function in one statement (reference: internal/xsql func_invoker)."""

    name: str
    args: List[Expr] = field(default_factory=list)
    func_id: int = 0
    # FILTER(WHERE cond) on aggregate calls
    filter: Optional[Expr] = None
    # OVER (PARTITION BY ... [WHEN cond]) on analytic calls
    partition: List[Expr] = field(default_factory=list)
    when: Optional[Expr] = None

    def children(self) -> List[Expr]:
        out = list(self.args)
        if self.filter is not None:
            out.append(self.filter)
        out.extend(self.partition)
        if self.when is not None:
            out.append(self.when)
        return out


OPERATORS = {
    "+", "-", "*", "/", "%", "&", "|", "^",
    "AND", "OR", "=", "!=", "<", "<=", ">", ">=",
}

# precedence mirrors pkg/ast/token.go:303-318 (higher binds tighter)
PRECEDENCE = {
    "OR": 1,
    "AND": 2,
    "=": 3, "!=": 3, "<": 3, "<=": 3, ">": 3, ">=": 3,
    "IN": 3, "NOT IN": 3, "BETWEEN": 3, "NOT BETWEEN": 3,
    "LIKE": 3, "NOT LIKE": 3,
    "+": 4, "-": 4, "|": 4, "^": 4,
    "*": 5, "/": 5, "%": 5, "&": 5, "[]": 5, "->": 5, ".": 5,
}


def walk(expr: Optional[Expr]) -> Iterator[Expr]:
    """Pre-order traversal of an expression tree."""
    if expr is None:
        return
    stack = [expr]
    while stack:
        node = stack.pop()
        yield node
        stack.extend(reversed(node.children()))


# ------------------------------------------------------------------ statements
class WindowType(str, Enum):
    NOT_WINDOW = "NOT_WINDOW"
    TUMBLING_WINDOW = "TUMBLING_WINDOW"
    HOPPING_WINDOW = "HOPPING_WINDOW"
    SLIDING_WINDOW = "SLIDING_WINDOW"
    SESSION_WINDOW = "SESSION_WINDOW"
    COUNT_WINDOW = "COUNT_WINDOW"
    STATE_WINDOW = "STATE_WINDOW"


@dataclass
class Window:
    """Window spec (reference: pkg/ast/statement.go:213-230).
    Length/Interval in units of `time_unit` except COUNT (row counts)."""

    window_type: WindowType
    time_unit: Optional[str] = None  # DD/HH/MI/SS/MS
    length: Optional[int] = None
    interval: Optional[int] = None
    delay: int = 0
    filter: Optional[Expr] = None  # FILTER(WHERE ...) on the window
    trigger_condition: Optional[Expr] = None  # sliding OVER(WHEN ...)
    begin_condition: Optional[Expr] = None  # state window
    emit_condition: Optional[Expr] = None  # state window

    def length_ms(self) -> int:
        from ..utils.timex import unit_to_ms

        return (self.length or 0) * unit_to_ms(self.time_unit or "ms")

    def interval_ms(self) -> int:
        from ..utils.timex import unit_to_ms

        if not self.interval:
            return 0
        return self.interval * unit_to_ms(self.time_unit or "ms")

    def delay_ms(self) -> int:
        from ..utils.timex import unit_to_ms

        return (self.delay or 0) * unit_to_ms(self.time_unit or "ms")


@dataclass
class Field:
    """SELECT field: expression + output name (+ AS alias flag)."""

    expr: Expr
    name: str = ""
    alias: str = ""
    invisible: bool = False

    @property
    def output_name(self) -> str:
        return self.alias or self.name


@dataclass
class Table:
    name: str
    alias: str = ""

    @property
    def ref_name(self) -> str:
        return self.alias or self.name


class JoinType(str, Enum):
    INNER = "INNER"
    LEFT = "LEFT"
    RIGHT = "RIGHT"
    FULL = "FULL"
    CROSS = "CROSS"


@dataclass
class Join:
    table: Table
    join_type: JoinType
    on: Optional[Expr] = None


@dataclass
class Dimension:
    expr: Expr


@dataclass
class SortField:
    name: str
    stream: str = ""
    ascending: bool = True
    expr: Optional[Expr] = None


@dataclass
class SelectStatement:
    fields: List[Field] = field(default_factory=list)
    sources: List[Table] = field(default_factory=list)
    joins: List[Join] = field(default_factory=list)
    condition: Optional[Expr] = None  # WHERE
    dimensions: List[Dimension] = field(default_factory=list)  # GROUP BY (non-window)
    window: Optional[Window] = None
    having: Optional[Expr] = None
    sorts: List[SortField] = field(default_factory=list)
    limit: Optional[int] = None

    def expressions(self) -> Iterator[Expr]:
        """All expression roots of the statement."""
        for f in self.fields:
            yield f.expr
        if self.condition is not None:
            yield self.condition
        for d in self.dimensions:
            yield d.expr
        if self.window is not None:
            for e in (
                self.window.filter,
                self.window.trigger_condition,
                self.window.begin_condition,
                self.window.emit_condition,
            ):
                if e is not None:
                    yield e
        for j in self.joins:
            if j.on is not None:
                yield j.on
        if self.having is not None:
            yield self.having
        for s in self.sorts:
            if s.expr is not None:
                yield s.expr


# -------------------------------------------------------------------- stream DDL
@dataclass
class StreamField:
    name: str
    type: DataType
    elem_type: Optional[DataType] = None
    fields: List["StreamField"] = field(default_factory=list)


@dataclass
class StreamOptions:
    """WITH (...) options (reference: pkg/ast/sourceStmt.go StreamTokens)."""

    datasource: str = ""
    key: str = ""
    format: str = "json"
    conf_key: str = ""
    type: str = ""  # source connector type; default mqtt in reference
    strict_validation: bool = False
    timestamp: str = ""  # event-time column
    timestamp_format: str = ""
    retain_size: int = 0
    shared: bool = False
    schemaid: str = ""
    kind: str = ""
    delimiter: str = ""

    def to_dict(self) -> dict:
        return {k: v for k, v in self.__dict__.items()}


@dataclass
class StreamStmt:
    name: str
    fields: List[StreamField] = field(default_factory=list)
    options: StreamOptions = field(default_factory=StreamOptions)
    is_table: bool = False


@dataclass
class ShowStmt:
    target: str  # STREAMS | TABLES


@dataclass
class DescribeStmt:
    target: str  # STREAM | TABLE
    name: str


@dataclass
class DropStmt:
    target: str
    name: str


@dataclass
class ExplainStmt:
    target: str
    name: str


Statement = Any  # SelectStatement | StreamStmt | ShowStmt | ...


def is_aggregate_call(name: str) -> bool:
    from ..functions import registry

    return registry.is_aggregate(name)


def has_aggregate(expr: Optional[Expr]) -> bool:
    """Does this expression contain an aggregate function call
    (reference: internal/xsql/checkAgg.go)?"""
    for node in walk(expr):
        if isinstance(node, Call) and is_aggregate_call(node.name):
            return True
    return False
