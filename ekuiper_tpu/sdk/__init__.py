"""Portable-plugin SDK — analogue of the reference Python SDK (sdk/python/ekuiper).

A portable plugin is a separate process in any language that speaks the
framed-IPC protocol (plugin/ipc.py). This SDK is the Python binding:

    from ekuiper_tpu.sdk import Function, Source, Sink, plugin_main

    class Rev(Function):
        def exec(self, args, ctx): return args[0][::-1]

    plugin_main({"name": "sample", "functions": {"rev": Rev},
                 "sources": {...}, "sinks": {...}})

Symbols are served on demand: the host sends start/stop-symbol commands over
the control channel (reference: internal/plugin/portable/runtime/connection.go:56-122,
sdk/python/ekuiper/runtime/plugin.py:32-50).
"""
from .api import Function, Sink, Source
from .runtime import plugin_main

__all__ = ["Function", "Source", "Sink", "plugin_main"]
