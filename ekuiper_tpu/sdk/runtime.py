"""Plugin-side runtime loop — analogue of sdk/python/ekuiper/runtime/plugin.py.

Dials the host's control channel, handshakes, then serves start/stop-symbol
commands. Each started symbol runs in its own thread:

  function  PAIR  dial ipc host endpoint; loop: recv {"func","args"} ->
            reply {"state","result"}  (reference: runtime/function.py)
  source    PUSH  dial; run Source.open(emit) pushing JSON tuples
  sink      PULL  dial; loop recv JSON rows -> Sink.collect

Wire protocol (JSON frames, reference: portable/runtime/function.go:106-134):
  control command  {"cmd": "start"|"stop", "ctrl": {symbolName, pluginType,
                    meta:{ruleId,opId,instanceId}, dataSource, config}}
  control reply    {"state": "ok"} | {"state": "error", "result": msg}
"""
from __future__ import annotations

import json
import sys
import threading
import traceback
from typing import Any, Dict

from ..plugin import ipc


def _reply_ok(sock) -> None:
    sock.send(json.dumps({"state": "ok"}).encode())


def _reply_err(sock, msg: str) -> None:
    sock.send(json.dumps({"state": "error", "result": msg}).encode())


class _SymbolRunner:
    def __init__(self, name: str, kind: str, inst: Any, ctrl: Dict[str, Any]) -> None:
        self.name = name
        self.kind = kind
        self.inst = inst
        self.ctrl = ctrl
        self.stopped = threading.Event()
        self.thread = threading.Thread(target=self._run, daemon=True, name=f"sym-{name}")

    def start(self) -> None:
        self.thread.start()

    def stop(self) -> None:
        self.stopped.set()
        try:
            self.inst.close()
        except Exception:
            pass

    def _channel_url(self) -> str:
        meta = self.ctrl.get("meta") or {}
        if self.kind == "function":
            return ipc.ipc_url(f"func_{self.ctrl['symbolName']}")
        tag = f"{meta.get('ruleId','r')}_{meta.get('opId','o')}_{meta.get('instanceId',0)}"
        return ipc.ipc_url(f"{self.kind}_{tag}")

    def _run(self) -> None:
        try:
            if self.kind == "function":
                self._run_function()
            elif self.kind == "source":
                self._run_source()
            else:
                self._run_sink()
        except (ipc.IpcClosed, ipc.IpcTimeout):
            pass
        except Exception:
            traceback.print_exc()

    def _run_function(self) -> None:
        sock = ipc.Socket(ipc.PAIR)
        sock.dial(self._channel_url(), 10_000)
        try:
            while not self.stopped.is_set():
                try:
                    raw = sock.recv(500)
                except ipc.IpcTimeout:
                    continue
                req = json.loads(raw)
                fname, fargs = req.get("func"), req.get("args", [])
                try:
                    if fname == "Validate":
                        err = self.inst.validate(fargs)
                        res = {"state": "ok" if not err else "error", "result": err}
                    elif fname == "Exec":
                        ctx = fargs[-1] if fargs else {}
                        res = {"state": "ok", "result": self.inst.exec(fargs[:-1], ctx)}
                    elif fname == "IsAggregate":
                        res = {"state": "ok", "result": self.inst.is_aggregate()}
                    else:
                        res = {"state": "error", "result": f"unknown func {fname}"}
                except Exception as e:
                    res = {"state": "error", "result": str(e)}
                sock.send(json.dumps(res, default=str).encode())
        finally:
            sock.close()

    def _run_source(self) -> None:
        sock = ipc.Socket(ipc.PUSH)
        sock.dial(self._channel_url(), 10_000)
        self.inst.configure(self.ctrl.get("dataSource", ""), self.ctrl.get("config") or {})

        def emit(data: Any) -> None:
            sock.send(json.dumps(data, default=str).encode())

        try:
            self.inst.open(emit, self.stopped.is_set)
        finally:
            sock.close()

    def _run_sink(self) -> None:
        sock = ipc.Socket(ipc.PULL)
        sock.dial(self._channel_url(), 10_000)
        self.inst.configure(self.ctrl.get("config") or {})
        self.inst.open()
        try:
            while not self.stopped.is_set():
                try:
                    raw = sock.recv(500)
                except ipc.IpcTimeout:
                    continue
                self.inst.collect(json.loads(raw))
        finally:
            sock.close()


def plugin_main(spec: Dict[str, Any]) -> None:
    """Serve the plugin until the host closes the control channel.

    spec: {"name": str, "functions": {sym: class}, "sources": {...}, "sinks": {...}}
    """
    name = spec["name"]
    ctrl_sock = ipc.Socket(ipc.PAIR)
    ctrl_sock.dial(ipc.ipc_url(f"plugin_{name}"), 15_000)
    # handshake (reference: plugin connects then reports status)
    ctrl_sock.send(json.dumps({"status": "ok", "name": name}).encode())

    runners: Dict[str, _SymbolRunner] = {}
    kinds = {"functions": "function", "sources": "source", "sinks": "sink"}
    try:
        while True:
            try:
                raw = ctrl_sock.recv(1000)
            except ipc.IpcTimeout:
                continue
            cmd = json.loads(raw)
            op, ctrl = cmd.get("cmd"), cmd.get("ctrl") or {}
            sym = ctrl.get("symbolName", "")
            if op == "start":
                kind_key = ctrl.get("pluginType", "functions")
                kind = kinds.get(kind_key, kind_key)
                reg = spec.get(kind_key) or spec.get(kind + "s") or {}
                cls = reg.get(sym)
                if cls is None:
                    _reply_err(ctrl_sock, f"symbol {sym} not found in plugin {name}")
                    continue
                key = f"{sym}:{json.dumps(ctrl.get('meta') or {}, sort_keys=True)}"
                runner = _SymbolRunner(sym, kind, cls(), ctrl)
                runners[key] = runner
                runner.start()
                _reply_ok(ctrl_sock)
            elif op == "stop":
                key = f"{sym}:{json.dumps(ctrl.get('meta') or {}, sort_keys=True)}"
                r = runners.pop(key, None)
                if r:
                    r.stop()
                _reply_ok(ctrl_sock)
            elif op == "ping":
                _reply_ok(ctrl_sock)
            else:
                _reply_err(ctrl_sock, f"unknown cmd {op}")
    except (ipc.IpcClosed, KeyboardInterrupt):
        pass
    finally:
        for r in runners.values():
            r.stop()
        ctrl_sock.close()
        sys.exit(0)
