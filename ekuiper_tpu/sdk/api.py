"""Plugin-side contract API — mirrors the reference SDK classes
(sdk/python/ekuiper/function.py:21-37, source.py, sink.py)."""
from __future__ import annotations

from typing import Any, Callable, Dict, List


class Function:
    """A SQL function served by this plugin (reference: function.py:21-37)."""

    def validate(self, args: List[Any]) -> str:
        """Return '' if args are acceptable, else an error message."""
        return ""

    def exec(self, args: List[Any], ctx: Dict[str, Any]) -> Any:
        raise NotImplementedError

    def is_aggregate(self) -> bool:
        return False


class Source:
    """A push source served by this plugin (reference: source.py)."""

    def configure(self, datasource: str, conf: Dict[str, Any]) -> None:
        pass

    def open(self, emit: Callable[[Any], None], closed: Callable[[], bool]) -> None:
        """Run the ingest loop; call emit(dict) per tuple; poll closed()."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class Sink:
    """A collector sink served by this plugin (reference: sink.py)."""

    def configure(self, conf: Dict[str, Any]) -> None:
        pass

    def open(self) -> None:
        pass

    def collect(self, data: Any) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass
