// ekipc — framed message transport over unix-domain / TCP sockets.
//
// Native analogue of the reference's nanomsg (NNG) layer
// (reference: pkg/nng/sock.go:37-148, internal/plugin/portable/runtime/connection.go)
// re-designed for the TPU build's host<->plugin-worker boundary:
//   PAIR      bidirectional, single peer (control + function channels)
//   PUSH/PULL one-way; the PULL side fans-in frames from N dialed peers
//             (plugin sources push micro-batches into the host)
//
// Wire format: 4-byte little-endian length prefix + payload.
// The host always listens (creates the ipc:// endpoint), workers dial —
// mirroring CreateSourceChannel / CreateSinkChannel / CreateFunctionChannel
// (connection.go:182-225).
//
// Exported C ABI (ctypes-friendly):
//   int  eks_new(int proto)                     proto: 0 PAIR, 1 PUSH, 2 PULL
//   int  eks_listen(int s, const char *url)
//   int  eks_dial(int s, const char *url, int timeout_ms)
//   int  eks_send(int s, const void *buf, int len, int timeout_ms)
//   long eks_recv(int s, unsigned char **out, int timeout_ms)  // malloc'd; free with eks_free_msg
//   void eks_free_msg(unsigned char *p)
//   int  eks_close(int s)
// Return codes: >=0 ok; -1 error; -2 timeout; -3 closed/EOF; -4 bad handle.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string>
#include <sys/socket.h>
#include <sys/time.h>
#include <sys/un.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr int EK_OK = 0, EK_ERR = -1, EK_TIMEOUT = -2, EK_CLOSED = -3, EK_BADH = -4;
constexpr uint32_t MAX_FRAME = 1u << 30;  // 1 GiB sanity bound

enum Proto { PAIR = 0, PUSH = 1, PULL = 2 };

struct Conn {
  int fd = -1;
  // partial-frame receive state (a poll may surface only part of a frame)
  std::string inbuf;
};

struct Sock {
  int proto = PAIR;
  int listen_fd = -1;
  std::string unlink_path;  // ipc path to remove on close
  std::vector<Conn> conns;
  std::mutex mu;        // state: conns vector, fds
  std::mutex send_mu;   // serialize senders
  std::mutex recv_mu;   // serialize receivers
  bool closed = false;
  int refs = 0;  // in-flight ops holding this Sock (guarded by g_mu)
};

std::mutex g_mu;
std::vector<Sock *> g_socks;

Sock *get(int h) {
  std::lock_guard<std::mutex> l(g_mu);
  if (h < 0 || h >= (int)g_socks.size()) return nullptr;
  Sock *s = g_socks[h];
  if (s) s->refs++;
  return s;
}

void put(Sock *s) {
  std::lock_guard<std::mutex> l(g_mu);
  s->refs--;
}

// RAII guard so every exported entry point releases its ref on return.
struct Ref {
  Sock *s;
  explicit Ref(Sock *sock) : s(sock) {}
  ~Ref() {
    if (s) put(s);
  }
};

int64_t now_ms() {
  struct timeval tv;
  gettimeofday(&tv, nullptr);
  return (int64_t)tv.tv_sec * 1000 + tv.tv_usec / 1000;
}

// url: "ipc:///tmp/x.ipc" or "tcp://127.0.0.1:5555"
int parse_url(const char *url, struct sockaddr_storage *ss, socklen_t *slen,
              int *family, std::string *ipc_path) {
  std::string u(url ? url : "");
  if (u.rfind("ipc://", 0) == 0) {
    std::string path = u.substr(6);
    auto *sa = (struct sockaddr_un *)ss;
    if (path.size() + 1 > sizeof(sa->sun_path)) return EK_ERR;
    memset(sa, 0, sizeof(*sa));
    sa->sun_family = AF_UNIX;
    memcpy(sa->sun_path, path.c_str(), path.size() + 1);
    *slen = sizeof(sa->sun_family) + path.size() + 1;
    *family = AF_UNIX;
    *ipc_path = path;
    return EK_OK;
  }
  if (u.rfind("tcp://", 0) == 0) {
    std::string hp = u.substr(6);
    auto colon = hp.rfind(':');
    if (colon == std::string::npos) return EK_ERR;
    std::string host = hp.substr(0, colon);
    int port = atoi(hp.c_str() + colon + 1);
    auto *sa = (struct sockaddr_in *)ss;
    memset(sa, 0, sizeof(*sa));
    sa->sin_family = AF_INET;
    sa->sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host.c_str(), &sa->sin_addr) != 1) return EK_ERR;
    *slen = sizeof(*sa);
    *family = AF_INET;
    return EK_OK;
  }
  return EK_ERR;
}

void set_nonblock(int fd, bool nb) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, nb ? (fl | O_NONBLOCK) : (fl & ~O_NONBLOCK));
}

// Blocking-with-deadline write of the whole buffer.
int write_full(int fd, const uint8_t *buf, size_t len, int64_t deadline) {
  size_t off = 0;
  while (off < len) {
    struct pollfd p{fd, POLLOUT, 0};
    int64_t left = deadline - now_ms();
    if (deadline >= 0 && left <= 0) return EK_TIMEOUT;
    int pr = poll(&p, 1, deadline < 0 ? -1 : (int)left);
    if (pr < 0) {
      if (errno == EINTR) continue;
      return EK_ERR;
    }
    if (pr == 0) return EK_TIMEOUT;
    ssize_t n = send(fd, buf + off, len - off, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK) continue;
      return (errno == EPIPE || errno == ECONNRESET) ? EK_CLOSED : EK_ERR;
    }
    off += (size_t)n;
  }
  return EK_OK;
}

// Try to pull whatever bytes are available into c->inbuf (nonblocking fd).
// Returns EK_OK (made progress or nothing to read), EK_CLOSED on EOF.
int drain_into(Conn *c) {
  char tmp[65536];
  for (;;) {
    ssize_t n = recv(c->fd, tmp, sizeof(tmp), 0);
    if (n > 0) {
      c->inbuf.append(tmp, (size_t)n);
      if (n < (ssize_t)sizeof(tmp)) return EK_OK;
      continue;
    }
    if (n == 0) return EK_CLOSED;
    if (errno == EAGAIN || errno == EWOULDBLOCK) return EK_OK;
    if (errno == EINTR) continue;
    return EK_CLOSED;
  }
}

// If a full frame sits in c->inbuf, pop it into *out/*outlen (malloc'd).
bool pop_frame(Conn *c, uint8_t **out, int64_t *outlen) {
  if (c->inbuf.size() < 4) return false;
  uint32_t len;
  memcpy(&len, c->inbuf.data(), 4);
  if (len > MAX_FRAME) {  // corrupt stream — drop connection semantics
    *outlen = EK_ERR;
    *out = nullptr;
    return true;
  }
  if (c->inbuf.size() < 4 + (size_t)len) return false;
  auto *p = (uint8_t *)malloc(len ? len : 1);
  memcpy(p, c->inbuf.data() + 4, len);
  c->inbuf.erase(0, 4 + (size_t)len);
  *out = p;
  *outlen = len;
  return true;
}

}  // namespace

extern "C" {

int eks_new(int proto) {
  if (proto < PAIR || proto > PULL) return EK_ERR;
  auto *s = new Sock();
  s->proto = proto;
  std::lock_guard<std::mutex> l(g_mu);
  // reclaim a slot whose socket is closed and no longer referenced — keeps
  // the table bounded under long-lived hosts that churn plugin channels
  for (size_t i = 0; i < g_socks.size(); ++i) {
    if (g_socks[i] && g_socks[i]->closed && g_socks[i]->refs == 0) {
      delete g_socks[i];
      g_socks[i] = s;
      return (int)i;
    }
  }
  g_socks.push_back(s);
  return (int)g_socks.size() - 1;
}

int eks_listen(int h, const char *url) {
  Sock *s = get(h);
  Ref ref(s);
  if (!s) return EK_BADH;
  struct sockaddr_storage ss;
  socklen_t slen;
  int family;
  std::string ipc_path;
  if (parse_url(url, &ss, &slen, &family, &ipc_path) != EK_OK) return EK_ERR;
  int fd = socket(family, SOCK_STREAM, 0);
  if (fd < 0) return EK_ERR;
  if (family == AF_UNIX && !ipc_path.empty()) unlink(ipc_path.c_str());
  if (family == AF_INET) {
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  }
  if (bind(fd, (struct sockaddr *)&ss, slen) < 0 || listen(fd, 64) < 0) {
    close(fd);
    return EK_ERR;
  }
  set_nonblock(fd, true);
  std::lock_guard<std::mutex> l(s->mu);
  s->listen_fd = fd;
  s->unlink_path = ipc_path;
  return EK_OK;
}

int eks_dial(int h, const char *url, int timeout_ms) {
  Sock *s = get(h);
  Ref ref(s);
  if (!s) return EK_BADH;
  struct sockaddr_storage ss;
  socklen_t slen;
  int family;
  std::string ipc_path;
  if (parse_url(url, &ss, &slen, &family, &ipc_path) != EK_OK) return EK_ERR;
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  // retry loop: the listener may not exist yet (worker started first)
  for (;;) {
    int fd = socket(family, SOCK_STREAM, 0);
    if (fd < 0) return EK_ERR;
    if (connect(fd, (struct sockaddr *)&ss, slen) == 0) {
      set_nonblock(fd, true);
      if (family == AF_INET) {
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      }
      std::lock_guard<std::mutex> l(s->mu);
      s->conns.push_back(Conn{fd, {}});
      return EK_OK;
    }
    close(fd);
    if (deadline >= 0 && now_ms() >= deadline) return EK_TIMEOUT;
    usleep(20 * 1000);
  }
}

static void accept_pending(Sock *s) {
  if (s->listen_fd < 0) return;
  for (;;) {
    int c = accept(s->listen_fd, nullptr, nullptr);
    if (c < 0) return;
    set_nonblock(c, true);
    s->conns.push_back(Conn{c, {}});
  }
}

int eks_send(int h, const void *buf, int len, int timeout_ms) {
  Sock *s = get(h);
  Ref ref(s);
  if (!s) return EK_BADH;
  if (len < 0) return EK_ERR;
  std::lock_guard<std::mutex> sl(s->send_mu);
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  int fd = -1;
  for (;;) {
    {
      std::lock_guard<std::mutex> l(s->mu);
      if (s->closed) return EK_CLOSED;
      accept_pending(s);
      // send to the most recent live connection (single-peer semantics;
      // PUSH host->worker and PAIR both have exactly one peer)
      if (!s->conns.empty()) fd = s->conns.back().fd;
    }
    if (fd >= 0) break;
    if (deadline >= 0 && now_ms() >= deadline) return EK_TIMEOUT;
    usleep(10 * 1000);
  }
  uint32_t hdr = (uint32_t)len;
  std::string frame;
  frame.reserve(4 + (size_t)len);
  frame.append((char *)&hdr, 4);
  frame.append((const char *)buf, (size_t)len);
  int rc = write_full(fd, (const uint8_t *)frame.data(), frame.size(), deadline);
  if (rc == EK_CLOSED) {
    std::lock_guard<std::mutex> l(s->mu);
    for (auto it = s->conns.begin(); it != s->conns.end(); ++it)
      if (it->fd == fd) {
        close(fd);
        s->conns.erase(it);
        break;
      }
  }
  return rc;
}

int64_t eks_recv(int h, uint8_t **out, int timeout_ms) {
  Sock *s = get(h);
  Ref ref(s);
  if (!s) return EK_BADH;
  std::lock_guard<std::mutex> rl(s->recv_mu);
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  for (;;) {
    std::vector<struct pollfd> pfds;
    {
      std::lock_guard<std::mutex> l(s->mu);
      if (s->closed) return EK_CLOSED;
      accept_pending(s);
      // fast path: a complete frame may already be buffered
      for (size_t i = 0; i < s->conns.size();) {
        int64_t n;
        uint8_t *p;
        if (pop_frame(&s->conns[i], &p, &n)) {
          if (n < 0) {  // corrupt stream — drop the connection, keep going
            close(s->conns[i].fd);
            s->conns.erase(s->conns.begin() + i);
            continue;
          }
          *out = p;
          return n;
        }
        ++i;
      }
      if (s->listen_fd >= 0) pfds.push_back({s->listen_fd, POLLIN, 0});
      for (auto &c : s->conns) pfds.push_back({c.fd, POLLIN, 0});
    }
    int64_t left = deadline < 0 ? -1 : deadline - now_ms();
    if (deadline >= 0 && left <= 0) return EK_TIMEOUT;
    if (pfds.empty()) {
      usleep(10 * 1000);  // nothing connected yet — wait for a dialer
      continue;
    }
    int pr = poll(pfds.data(), pfds.size(), left < 0 ? 250 : (int)std::min<int64_t>(left, 250));
    if (pr < 0 && errno != EINTR) return EK_ERR;
    std::lock_guard<std::mutex> l(s->mu);
    if (s->closed) return EK_CLOSED;
    accept_pending(s);
    for (size_t i = 0; i < s->conns.size();) {
      Conn &c = s->conns[i];
      int rc = drain_into(&c);
      int64_t n;
      uint8_t *p;
      if (pop_frame(&c, &p, &n)) {
        if (n < 0) {  // corrupt frame — kill connection
          close(c.fd);
          s->conns.erase(s->conns.begin() + i);
          continue;
        }
        *out = p;
        return n;
      }
      // peer hung up and no complete frame is buffered (pop_frame above
      // returned false) — a partial frame can never complete, so drop the
      // conn now; keeping it would busy-spin on a dead POLLIN fd
      if (rc == EK_CLOSED) {
        close(c.fd);
        s->conns.erase(s->conns.begin() + i);
        // a PAIR peer hanging up means the channel is done
        if (s->proto == PAIR && s->conns.empty() && s->listen_fd < 0) return EK_CLOSED;
        continue;
      }
      ++i;
    }
  }
}

void eks_free_msg(uint8_t *p) { free(p); }

int eks_close(int h) {
  Sock *s = get(h);
  Ref ref(s);
  if (!s) return EK_BADH;
  std::lock_guard<std::mutex> l(s->mu);
  if (s->closed) return EK_OK;
  s->closed = true;
  if (s->listen_fd >= 0) close(s->listen_fd);
  for (auto &c : s->conns) close(c.fd);
  s->conns.clear();
  if (!s->unlink_path.empty()) unlink(s->unlink_path.c_str());
  return EK_OK;
}

}  // extern "C"
