// ekjsoncol — native columnar JSON decoder for the ingest hot path.
//
// The TPU data plane wants columns, not dicts: the Python chain
// (json.loads -> list-of-dict -> per-column list comps, ~1.5us/row of
// GIL-bound work) caps full-pipe ingest far below the fused kernel's rate.
// This extension parses a run of raw JSON object payloads DIRECTLY into
// typed numpy columns + validity masks in one C pass:
//
//   decode(payloads: list[bytes], fields: ((name, type), ...), shards=1)
//     -> (columns: dict[str, ndarray], valid: dict[str, ndarray],
//         bad: ndarray[bool])
//
// shards > 1 runs the GIL-free parse pass over `shards` contiguous slices
// of the payload list on native threads concurrently. Every shard writes
// into ITS row range of the one shared numpy allocation (rows are disjoint
// by construction — no per-shard buffers, no concat), keeps a private
// scratch/arena/StrRef list, and the final GIL'd intern pass walks shards
// in slice order so string interning (and therefore the output) is
// byte-identical to the single-thread path for any shard count.
//
// field types: 0=FLOAT(f32) 1=BIGINT(i64) 2=BOOLEAN(bool) 3=STRING(object)
// Semantics mirror data/cast.py CONVERT_ALL coercion (the row-path
// preprocessor): numeric strings parse, bools in {0,1} accept, numbers
// stringify with shortest round-trip (to_chars), null/missing -> invalid,
// uncastable value -> row marked bad (caller drops it). Rows that need
// semantics C can't reproduce (int64 overflow -> Python bigint) flag the
// whole batch for Python fallback by raising ekjsoncol.Fallback.
//
// Repeated string values (10k device ids over millions of rows) intern
// through a local hash table, so the object column mostly holds INCREF'd
// existing PyUnicode objects instead of fresh allocations.
//
// Reference analogue: the schema-aware fastjson converter
// (internal/converter/json) feeding SliceTuple columns.
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>

#include <cctype>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum FieldType { F_FLOAT = 0, F_BIGINT = 1, F_BOOL = 2, F_STRING = 3 };

struct Field {
  std::string name;
  int type;
  // output buffers (borrowed from the numpy arrays)
  float* f32 = nullptr;
  int64_t* i64 = nullptr;
  unsigned char* b8 = nullptr;
  PyObject** obj = nullptr;
  unsigned char* valid = nullptr;
};

struct StrKey {
  const char* p;
  size_t n;
  bool operator==(const StrKey& o) const {
    return n == o.n && std::memcmp(p, o.p, n) == 0;
  }
};
struct StrKeyHash {
  size_t operator()(const StrKey& k) const {
    // FNV-1a
    size_t h = 1469598103934665603ull;
    for (size_t i = 0; i < k.n; i++) {
      h ^= (unsigned char)k.p[i];
      h *= 1099511628211ull;
    }
    return h;
  }
};

struct Parser {
  const char* p;
  const char* end;
  bool fallback = false;  // batch needs the Python path
  std::string scratch;    // unescape buffer

  explicit Parser(const char* b, const char* e) : p(b), end(e) {}

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      p++;
  }
  bool lit(const char* s, size_t n) {
    if ((size_t)(end - p) < n || std::memcmp(p, s, n) != 0) return false;
    p += n;
    return true;
  }

  // Parse a JSON string (after the opening quote). Returns pointer/len of
  // the decoded content — either a borrowed range of the input (no escapes,
  // the common case) or `scratch`.
  bool str_body(const char** out, size_t* out_n) {
    const char* start = p;
    while (p < end && *p != '"' && *p != '\\') p++;
    if (p < end && *p == '"') {  // fast path: no escapes
      *out = start;
      *out_n = (size_t)(p - start);
      p++;
      return true;
    }
    // slow path: unescape into scratch
    scratch.assign(start, (size_t)(p - start));
    while (p < end && *p != '"') {
      if (*p != '\\') {
        scratch.push_back(*p++);
        continue;
      }
      p++;
      if (p >= end) return false;
      char c = *p++;
      switch (c) {
        case '"': scratch.push_back('"'); break;
        case '\\': scratch.push_back('\\'); break;
        case '/': scratch.push_back('/'); break;
        case 'b': scratch.push_back('\b'); break;
        case 'f': scratch.push_back('\f'); break;
        case 'n': scratch.push_back('\n'); break;
        case 'r': scratch.push_back('\r'); break;
        case 't': scratch.push_back('\t'); break;
        case 'u': {
          if (end - p < 4) return false;
          unsigned cp = 0;
          for (int i = 0; i < 4; i++) {
            char h = *p++;
            cp <<= 4;
            if (h >= '0' && h <= '9') cp |= (unsigned)(h - '0');
            else if (h >= 'a' && h <= 'f') cp |= (unsigned)(h - 'a' + 10);
            else if (h >= 'A' && h <= 'F') cp |= (unsigned)(h - 'A' + 10);
            else return false;
          }
          if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 6 && p[0] == '\\' &&
              p[1] == 'u') {  // surrogate pair
            unsigned lo = 0;
            const char* q = p + 2;
            bool ok = true;
            for (int i = 0; i < 4; i++) {
              char h = q[i];
              lo <<= 4;
              if (h >= '0' && h <= '9') lo |= (unsigned)(h - '0');
              else if (h >= 'a' && h <= 'f') lo |= (unsigned)(h - 'a' + 10);
              else if (h >= 'A' && h <= 'F') lo |= (unsigned)(h - 'A' + 10);
              else { ok = false; break; }
            }
            if (ok && lo >= 0xDC00 && lo <= 0xDFFF) {
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              p = q + 4;
            }
          }
          // utf-8 encode
          if (cp < 0x80) scratch.push_back((char)cp);
          else if (cp < 0x800) {
            scratch.push_back((char)(0xC0 | (cp >> 6)));
            scratch.push_back((char)(0x80 | (cp & 0x3F)));
          } else if (cp < 0x10000) {
            scratch.push_back((char)(0xE0 | (cp >> 12)));
            scratch.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
            scratch.push_back((char)(0x80 | (cp & 0x3F)));
          } else {
            scratch.push_back((char)(0xF0 | (cp >> 18)));
            scratch.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
            scratch.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
            scratch.push_back((char)(0x80 | (cp & 0x3F)));
          }
          break;
        }
        default: return false;
      }
    }
    if (p >= end) return false;
    p++;  // closing quote
    *out = scratch.data();
    *out_n = scratch.size();
    return true;
  }

  // Skip any JSON value (for undeclared keys).
  bool skip_value() {
    ws();
    if (p >= end) return false;
    char c = *p;
    if (c == '"') {
      p++;
      const char* s;
      size_t n;
      return str_body(&s, &n);
    }
    if (c == '{' || c == '[') {
      char open = c, close = (c == '{') ? '}' : ']';
      int depth = 0;
      bool in_str = false;
      while (p < end) {
        char d = *p++;
        if (in_str) {
          if (d == '\\') { if (p < end) p++; }
          else if (d == '"') in_str = false;
        } else if (d == '"') in_str = true;
        else if (d == open) depth++;
        else if (d == close) {
          if (--depth == 0) return true;
        }
      }
      return false;
    }
    if (lit("true", 4) || lit("false", 5) || lit("null", 4)) return true;
    // number ('+'-prefixed forms are not JSON — json.loads rejects them)
    if (p < end && *p == '+') return false;
    const char* start = p;
    if (p < end && *p == '-') p++;
    while (p < end && (std::isdigit((unsigned char)*p) || *p == '.' ||
                       *p == 'e' || *p == 'E' || *p == '-' || *p == '+'))
      p++;
    return p > start;
  }
};

// shortest-round-trip double -> string, matching Python str(float) closely
void format_double(double v, std::string& out) {
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
  char buf[40];
  auto res = std::to_chars(buf, buf + sizeof(buf), v);
  out.assign(buf, res.ptr);
#else
  // no floating-point to_chars (GCC < 11): smallest %g precision that
  // parses back to exactly v — same shortest-round-trip contract
  char buf[40];
  for (int prec = 1; prec <= 17; prec++) {
    std::snprintf(buf, sizeof(buf), "%.*g", prec, v);
    if (std::strtod(buf, nullptr) == v) break;
  }
  out = buf;
#endif
}

struct Interner {
  std::unordered_map<StrKey, PyObject*, StrKeyHash> map;
  // owns key bytes — deque: element addresses are STABLE across growth
  // (a vector reallocation would move SSO strings and dangle StrKey.p)
  std::deque<std::string> storage;
  bool bad_utf8 = false;  // last get() failed UTF-8 validation (bad row)

  ~Interner() {
    for (auto& kv : map) Py_DECREF(kv.second);
  }
  PyObject* get(const char* s, size_t n) {  // returns NEW reference
    auto it = map.find(StrKey{s, n});
    if (it != map.end()) {
      Py_INCREF(it->second);
      return it->second;
    }
    // json.loads preserves lone \u-escape surrogates but raises on other
    // invalid UTF-8; surrogatepass mirrors that so both decode paths
    // classify the same payloads as bad (the Python path drops the row)
    PyObject* u = PyUnicode_DecodeUTF8(s, (Py_ssize_t)n, "surrogatepass");
    if (u == nullptr) {
      if (PyErr_ExceptionMatches(PyExc_UnicodeDecodeError)) {
        PyErr_Clear();
        bad_utf8 = true;
      }
      return nullptr;
    }
    if (map.size() < 262144) {  // bound the table
      storage.emplace_back(s, n);
      const std::string& owned = storage.back();
      Py_INCREF(u);
      map.emplace(StrKey{owned.data(), owned.size()}, u);
    }
    return u;
  }
};

// A string value discovered during the GIL-free parse pass: the row/field
// it belongs to and a byte span that stays valid until the GIL'd intern
// pass (either borrowed payload bytes or arena-owned unescaped bytes).
struct StrRef {
  npy_intp row;
  int field;
  const char* p;
  size_t n;
};

// Owns bytes for escaped/converted string values across the two passes.
// deque keeps element addresses stable under growth.
struct Arena {
  std::deque<std::string> items;
  const char* put(const char* s, size_t n) {
    items.emplace_back(s, n);
    return items.back().data();
  }
  const char* put(const std::string& s) {
    items.emplace_back(s);
    return items.back().data();
  }
};

// Parse one object payload into row r of the field buffers.
// Returns: 0 ok, 1 bad row (cast/shape error), 2 batch fallback.
// Runs WITHOUT the GIL: string values are recorded as StrRefs (payload
// spans or arena copies) and materialized in a later GIL'd intern pass.
int parse_row(Parser& ps, std::vector<Field>& fields, npy_intp r,
              std::vector<StrRef>& strs, Arena& arena, std::string& tmp) {
  ps.ws();
  if (ps.p < ps.end && *ps.p == '[')
    return 2;  // array payload: rows-per-payload is the python path's job
  if (ps.p >= ps.end || *ps.p != '{') return 1;
  ps.p++;
  ps.ws();
  if (ps.p < ps.end && *ps.p == '}') {
    ps.p++;
    ps.ws();
    return (ps.p == ps.end) ? 0 : 1;  // '{} garbage' is NOT a good row
  }
  while (true) {
    ps.ws();
    if (ps.p >= ps.end || *ps.p != '"') return 1;
    ps.p++;
    const char* key;
    size_t key_n;
    {
      // key may come from scratch; copy before value parsing reuses it
      const char* k;
      size_t kn;
      if (!ps.str_body(&k, &kn)) return 1;
      if (k == ps.scratch.data()) {
        tmp.assign(k, kn);
        key = tmp.data();
      } else {
        key = k;
      }
      key_n = kn;
    }
    ps.ws();
    if (ps.p >= ps.end || *ps.p != ':') return 1;
    ps.p++;
    Field* f = nullptr;
    for (auto& cand : fields) {
      if (cand.name.size() == key_n &&
          std::memcmp(cand.name.data(), key, key_n) == 0) {
        f = &cand;
        break;
      }
    }
    if (f == nullptr) {
      if (!ps.skip_value()) return 1;
    } else {
      ps.ws();
      if (ps.p >= ps.end) return 1;
      char c = *ps.p;
      if (c == 'n' && ps.lit("null", 4)) {
        // null -> invalid (valid[r] stays 0)
      } else if (c == '{' || c == '[') {
        return 1;  // nested value for a scalar field: cast error -> drop
      } else if (c == '"') {
        ps.p++;
        const char* s;
        size_t n;
        if (!ps.str_body(&s, &n)) return 1;
        switch (f->type) {
          case F_STRING: {
            // UTF-8 validity is checked at intern time (GIL pass); escaped
            // content lives in ps.scratch which the next string reuses, so
            // copy it into the arena now
            const char* sp = (s == ps.scratch.data()) ? arena.put(s, n) : s;
            strs.push_back({r, (int)(f - fields.data()), sp, n});
            f->valid[r] = 1;
            break;
          }
          case F_FLOAT: case F_BIGINT: {
            // cast.to_float/to_int accept numeric strings (CONVERT_ALL)
            tmp.assign(s, n);
            char* endp = nullptr;
            double v = std::strtod(tmp.c_str(), &endp);
            if (endp == tmp.c_str() || *endp != '\0') return 1;
            if (f->type == F_FLOAT) f->f32[r] = (float)v;
            else {
              if (v > 9.2233720368547e18 || v < -9.2233720368547e18)
                return 2;  // beyond int64: Python bigint semantics
              f->i64[r] = (int64_t)v;
            }
            f->valid[r] = 1;
            break;
          }
          case F_BOOL: {
            // to_bool(str): lowercase match on true/false/1/0
            std::string low(s, n);
            for (auto& ch : low) ch = (char)std::tolower((unsigned char)ch);
            if (low == "true" || low == "1") f->b8[r] = 1;
            else if (low == "false" || low == "0") f->b8[r] = 0;
            else return 1;
            f->valid[r] = 1;
            break;
          }
        }
      } else if (c == 't' || c == 'f') {
        bool v = (c == 't');
        if (!(v ? ps.lit("true", 4) : ps.lit("false", 5))) return 1;
        switch (f->type) {
          case F_BOOL: f->b8[r] = v ? 1 : 0; break;
          case F_FLOAT: f->f32[r] = v ? 1.0f : 0.0f; break;  // to_float(bool)
          case F_BIGINT: f->i64[r] = v ? 1 : 0; break;       // to_int(bool)
          case F_STRING: {
            strs.push_back({r, (int)(f - fields.data()),
                            v ? "true" : "false", v ? 4u : 5u});
            break;
          }
        }
        f->valid[r] = 1;
      } else {
        // number ('+'-prefixed forms are not JSON — json.loads rejects them)
        if (*ps.p == '+') return 1;
        const char* start = ps.p;
        if (*ps.p == '-') ps.p++;
        bool is_float = false;
        while (ps.p < ps.end &&
               (std::isdigit((unsigned char)*ps.p) || *ps.p == '.' ||
                *ps.p == 'e' || *ps.p == 'E' || *ps.p == '-' || *ps.p == '+')) {
          if (*ps.p == '.' || *ps.p == 'e' || *ps.p == 'E') is_float = true;
          ps.p++;
        }
        if (ps.p == start) return 1;
        tmp.assign(start, (size_t)(ps.p - start));
        switch (f->type) {
          case F_FLOAT: {
            char* endp = nullptr;
            double v = std::strtod(tmp.c_str(), &endp);
            if (*endp != '\0') return 1;
            f->f32[r] = (float)v;
            break;
          }
          case F_BIGINT: {
            if (!is_float) {
              errno = 0;
              char* endp = nullptr;
              long long v = std::strtoll(tmp.c_str(), &endp, 10);
              if (*endp != '\0') return 1;
              if (errno == ERANGE) return 2;  // Python bigint territory
              f->i64[r] = v;
            } else {
              char* endp = nullptr;
              double v = std::strtod(tmp.c_str(), &endp);
              if (*endp != '\0') return 1;
              if (v > 9.2233720368547e18 || v < -9.2233720368547e18) return 2;
              f->i64[r] = (int64_t)v;  // to_int truncates
            }
            break;
          }
          case F_BOOL: {
            // to_bool accepts numeric values equal to 0 or 1 only
            char* endp = nullptr;
            double v = std::strtod(tmp.c_str(), &endp);
            if (*endp != '\0' || (v != 0.0 && v != 1.0)) return 1;
            f->b8[r] = (v == 1.0) ? 1 : 0;
            break;
          }
          case F_STRING: {
            // to_string: integral floats render as ints, else str(float)
            std::string sv;
            if (!is_float) sv = tmp;
            else {
              char* endp = nullptr;
              double v = std::strtod(tmp.c_str(), &endp);
              if (*endp != '\0') return 1;
              if (std::isfinite(v) && v == std::floor(v) &&
                  std::fabs(v) < 9.2e18) {
                char b[32];
                auto res = std::to_chars(b, b + sizeof(b), (long long)v);
                sv.assign(b, res.ptr);
              } else {
                format_double(v, sv);
              }
            }
            strs.push_back({r, (int)(f - fields.data()),
                            arena.put(sv), sv.size()});
            break;
          }
        }
        f->valid[r] = 1;
      }
    }
    ps.ws();
    if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
    if (ps.p < ps.end && *ps.p == '}') { ps.p++; break; }
    return 1;
  }
  ps.ws();
  return (ps.p == ps.end) ? 0 : 1;  // trailing garbage -> bad row
}

PyObject* FallbackError = nullptr;

// Per-shard private parse state: everything the GIL-free pass touches that
// is not a disjoint row range of the shared output buffers.
struct Shard {
  npy_intp begin = 0;
  npy_intp end = 0;
  std::vector<StrRef> strs;
  Arena arena;
  bool fallback = false;
};

// Parse rows [sh.begin, sh.end) of the payload slice. Pure native code —
// runs with the GIL released, possibly on a std::thread.
void parse_shard(Shard& sh,
                 const std::vector<std::pair<const char*, Py_ssize_t>>& bufs,
                 std::vector<Field>& fields, unsigned char* bad) {
  std::string tmp;
  sh.strs.reserve((size_t)(sh.end - sh.begin));
  for (npy_intp r = sh.begin; r < sh.end; r++) {
    Parser ps(bufs[(size_t)r].first,
              bufs[(size_t)r].first + bufs[(size_t)r].second);
    int rc = parse_row(ps, fields, r, sh.strs, sh.arena, tmp);
    if (rc == 2) {
      sh.fallback = true;
      break;
    }
    if (rc == 1) {
      bad[r] = 1;
      for (auto& f : fields) f.valid[r] = 0;
    }
  }
}

PyObject* jc_decode(PyObject*, PyObject* args) {
  PyObject* payloads;
  PyObject* fields_spec;
  int n_shards = 1;
  if (!PyArg_ParseTuple(args, "OO|i", &payloads, &fields_spec, &n_shards))
    return nullptr;
  if (!PyList_Check(payloads) || !PyTuple_Check(fields_spec)) {
    PyErr_SetString(PyExc_TypeError, "decode(list[bytes], tuple[(name, type)])");
    return nullptr;
  }
  npy_intp n_rows = (npy_intp)PyList_GET_SIZE(payloads);
  Py_ssize_t n_fields = PyTuple_GET_SIZE(fields_spec);

  std::vector<Field> fields((size_t)n_fields);
  PyObject* cols = PyDict_New();
  PyObject* valids = PyDict_New();
  for (Py_ssize_t i = 0; i < n_fields; i++) {
    PyObject* spec = PyTuple_GET_ITEM(fields_spec, i);
    const char* name;
    int ftype;
    if (!PyArg_ParseTuple(spec, "si", &name, &ftype)) {
      Py_DECREF(cols); Py_DECREF(valids);
      return nullptr;
    }
    Field& f = fields[(size_t)i];
    f.name = name;
    f.type = ftype;
    int npy_type;
    switch (ftype) {
      case F_FLOAT: npy_type = NPY_FLOAT32; break;
      case F_BIGINT: npy_type = NPY_INT64; break;
      case F_BOOL: npy_type = NPY_BOOL; break;
      case F_STRING: npy_type = NPY_OBJECT; break;
      default:
        PyErr_SetString(PyExc_ValueError, "bad field type");
        Py_DECREF(cols); Py_DECREF(valids);
        return nullptr;
    }
    PyObject* arr = PyArray_ZEROS(1, &n_rows, npy_type, 0);
    PyObject* va = PyArray_ZEROS(1, &n_rows, NPY_BOOL, 0);
    if (arr == nullptr || va == nullptr) {
      Py_XDECREF(arr); Py_XDECREF(va);
      Py_DECREF(cols); Py_DECREF(valids);
      return nullptr;
    }
    void* data = PyArray_DATA((PyArrayObject*)arr);
    switch (ftype) {
      case F_FLOAT: f.f32 = (float*)data; break;
      case F_BIGINT: f.i64 = (int64_t*)data; break;
      case F_BOOL: f.b8 = (unsigned char*)data; break;
      case F_STRING: f.obj = (PyObject**)data; break;
    }
    f.valid = (unsigned char*)PyArray_DATA((PyArrayObject*)va);
    PyDict_SetItemString(cols, name, arr);
    PyDict_SetItemString(valids, name, va);
    Py_DECREF(arr);
    Py_DECREF(va);
  }
  PyObject* bad_arr = PyArray_ZEROS(1, &n_rows, NPY_BOOL, 0);
  if (bad_arr == nullptr) {
    Py_DECREF(cols); Py_DECREF(valids);
    return nullptr;
  }
  unsigned char* bad = (unsigned char*)PyArray_DATA((PyArrayObject*)bad_arr);

  // NaN-fill float columns (invalid rows must read as NaN, matching
  // from_messages); object columns pre-fill with None
  for (auto& f : fields) {
    if (f.type == F_FLOAT) {
      for (npy_intp r = 0; r < n_rows; r++) f.f32[r] = NAN;
    } else if (f.type == F_STRING) {
      for (npy_intp r = 0; r < n_rows; r++) {
        Py_INCREF(Py_None);
        f.obj[r] = Py_None;
      }
    }
  }

  // resolve payload buffers under the GIL; the caller owns the list and
  // must not mutate it during the call (the source's flush list is local).
  // bytes are immutable so borrowing their buffer across the GIL release
  // is safe; bytearrays can be resized by another thread (realloc frees
  // the buffer the parse would read) — copy those now, while we hold it.
  std::vector<std::pair<const char*, Py_ssize_t>> bufs((size_t)n_rows);
  Arena payload_copies;
  for (npy_intp r = 0; r < n_rows; r++) {
    PyObject* pl = PyList_GET_ITEM(payloads, r);
    if (PyBytes_Check(pl)) {
      bufs[(size_t)r] = {PyBytes_AS_STRING(pl), PyBytes_GET_SIZE(pl)};
    } else if (PyByteArray_Check(pl)) {
      Py_ssize_t bn = PyByteArray_GET_SIZE(pl);
      bufs[(size_t)r] = {
          payload_copies.put(PyByteArray_AS_STRING(pl), (size_t)bn), bn};
    } else {
      Py_DECREF(cols); Py_DECREF(valids); Py_DECREF(bad_arr);
      PyErr_SetString(FallbackError, "non-bytes payload");
      return nullptr;
    }
  }

  // pass 1 — parse WITHOUT the GIL: numeric/bool columns fill directly,
  // string values become StrRefs. This is the bulk of the work and runs
  // truly parallel to the engine's other Python threads (the fused node
  // worker, emit workers), which is what lets a byte-fed pipe keep the
  // device path busy (reference measures bytes-in end-to-end, README.md:98).
  // With shards > 1 the pass itself also fans out over native threads:
  // each shard owns a contiguous row slice of the SAME output buffers.
  if (n_shards < 1) n_shards = 1;
  if (n_shards > 32) n_shards = 32;
  // tiny batches: thread spawn would cost more than the parse
  while (n_shards > 1 && n_rows < (npy_intp)n_shards * 256) n_shards--;
  std::vector<Shard> shards((size_t)n_shards);
  {
    npy_intp chunk = (n_rows + n_shards - 1) / n_shards;
    for (int i = 0; i < n_shards; i++) {
      shards[(size_t)i].begin = std::min((npy_intp)i * chunk, n_rows);
      shards[(size_t)i].end = std::min((npy_intp)(i + 1) * chunk, n_rows);
    }
  }
  bool need_fallback = false;
  Py_BEGIN_ALLOW_THREADS
  if (n_shards == 1) {
    parse_shard(shards[0], bufs, fields, bad);
  } else {
    std::vector<std::thread> workers;
    workers.reserve((size_t)(n_shards - 1));
    try {
      for (int i = 1; i < n_shards; i++)
        workers.emplace_back(parse_shard, std::ref(shards[(size_t)i]),
                             std::cref(bufs), std::ref(fields), bad);
    } catch (const std::exception&) {
      // thread/resource exhaustion (EAGAIN): the un-spawned shards run
      // serially below — a slower decode, never a std::terminate (and
      // never an exception escaping the no-GIL region)
    }
    parse_shard(shards[0], bufs, fields, bad);
    for (size_t i = workers.size() + 1; i < (size_t)n_shards; i++)
      parse_shard(shards[i], bufs, fields, bad);
    for (auto& w : workers) w.join();
  }
  for (auto& sh : shards)
    if (sh.fallback) need_fallback = true;
  Py_END_ALLOW_THREADS
  if (need_fallback) {
    Py_DECREF(cols); Py_DECREF(valids); Py_DECREF(bad_arr);
    PyErr_SetString(FallbackError, "payload needs the python decoder");
    return nullptr;
  }

  // pass 2 — intern string values under the GIL: hash + incref per value
  // (hit path), PyUnicode decode only for novel strings. Invalid UTF-8
  // marks the row bad (json.loads parity), never a batch fallback.
  // Shards are walked in slice order, so the intern sequence (and the
  // bounded table's contents) matches the single-thread pass exactly.
  Interner intern;
  for (auto& sh : shards) {
    for (const StrRef& sr : sh.strs) {
      if (bad[sr.row]) continue;  // a later field already failed this row
      PyObject* u = intern.get(sr.p, sr.n);
      if (u == nullptr) {
        if (intern.bad_utf8) {
          intern.bad_utf8 = false;
          bad[sr.row] = 1;
          for (auto& f : fields) f.valid[sr.row] = 0;
          continue;
        }
        Py_DECREF(cols); Py_DECREF(valids); Py_DECREF(bad_arr);
        return nullptr;  // real error (e.g. MemoryError) already set
      }
      Field& f = fields[(size_t)sr.field];
      Py_XDECREF(f.obj[sr.row]);
      f.obj[sr.row] = u;
    }
  }
  PyObject* out = PyTuple_Pack(3, cols, valids, bad_arr);
  Py_DECREF(cols);
  Py_DECREF(valids);
  Py_DECREF(bad_arr);
  return out;
}

// ---------------------------------------------------------------------------
// Persistent per-stream key-slot table (GROUP BY dictionary encode).
//
// The Python KeyTable's steady-state encode is a C-level dict map per row
// (~7 ms per 64k batch) serialized on the fused worker thread. keytab_*
// moves that walk into one native pass over the decoded key column: a
// persistent byte-keyed hash table (key bytes -> dense int32 slot) plus a
// bounded pointer-identity cache over the interned PyUnicode objects the
// decoder emits (repeated device ids resolve by pointer hash, no byte
// compare). Newly-seen keys return as an ordered appendix so the Python
// KeyTable — which STAYS the source of truth for reverse decode,
// checkpointing, and every fallback path — bulk-syncs to identical slot
// ids. Normalization matches KeyTable._normalize: None encodes as "".
//
// Contract: encode(tab, keys_list) either completes fully or raises
// WITHOUT mutating the table (non-str/None elements, lone-surrogate
// strings -> ekjsoncol.Fallback; the caller runs the Python path).

struct KeyTab {
  std::unordered_map<StrKey, int32_t, StrKeyHash> byte_map;
  std::deque<std::string> storage;  // owns key bytes; stable addresses
  std::unordered_map<PyObject*, int32_t> ptr_cache;  // strong refs
  int64_t n = 0;  // slots assigned == byte_map.size()

  ~KeyTab() {
    // capsule destructors can run during interpreter teardown, when
    // touching refcounts is no longer safe
    if (Py_IsInitialized()) {
      for (auto& kv : ptr_cache) Py_DECREF(kv.first);
    }
  }
};

constexpr size_t kPtrCacheCap = 1u << 16;

void keytab_destruct(PyObject* cap) {
  auto* kt = (KeyTab*)PyCapsule_GetPointer(cap, "ekjsoncol.keytab");
  delete kt;
}

KeyTab* keytab_from(PyObject* cap) {
  return (KeyTab*)PyCapsule_GetPointer(cap, "ekjsoncol.keytab");
}

PyObject* kt_new(PyObject*, PyObject*) {
  return PyCapsule_New(new KeyTab(), "ekjsoncol.keytab", keytab_destruct);
}

PyObject* kt_len(PyObject*, PyObject* args) {
  PyObject* cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  KeyTab* kt = keytab_from(cap);
  if (kt == nullptr) return nullptr;
  return PyLong_FromLongLong((long long)kt->n);
}

PyObject* kt_clear(PyObject*, PyObject* args) {
  PyObject* cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  KeyTab* kt = keytab_from(cap);
  if (kt == nullptr) return nullptr;
  for (auto& kv : kt->ptr_cache) Py_DECREF(kv.first);
  kt->ptr_cache.clear();
  kt->byte_map.clear();
  kt->storage.clear();
  kt->n = 0;
  Py_RETURN_NONE;
}

PyObject* kt_encode(PyObject*, PyObject* args) {
  PyObject* cap;
  PyObject* seq;
  if (!PyArg_ParseTuple(args, "OO", &cap, &seq)) return nullptr;
  KeyTab* kt = keytab_from(cap);
  if (kt == nullptr) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "keytab_encode expects a sequence");
  if (fast == nullptr) return nullptr;
  npy_intp n = (npy_intp)PySequence_Fast_GET_SIZE(fast);
  PyObject** items = PySequence_Fast_ITEMS(fast);

  // pass 1 — validate + resolve key bytes BEFORE any table mutation, so a
  // reject leaves the table byte-identical to the Python-path history.
  // Exact str / None only: subclasses (np.str_) or other types keep the
  // Python dict semantics the native map can't reproduce.
  std::vector<std::pair<const char*, Py_ssize_t>> spans((size_t)n);
  for (npy_intp i = 0; i < n; i++) {
    PyObject* it = items[i];
    if (it == Py_None) {
      spans[(size_t)i] = {"", 0};  // KeyTable._normalize: None -> ""
      continue;
    }
    if (!PyUnicode_CheckExact(it)) {
      Py_DECREF(fast);
      PyErr_SetString(FallbackError, "non-string key");
      return nullptr;
    }
    Py_ssize_t sn = 0;
    const char* sp = PyUnicode_AsUTF8AndSize(it, &sn);
    if (sp == nullptr) {  // lone surrogates: not UTF-8 encodable
      PyErr_Clear();
      Py_DECREF(fast);
      PyErr_SetString(FallbackError, "non-encodable key");
      return nullptr;
    }
    spans[(size_t)i] = {sp, sn};
  }

  PyObject* slots_arr = PyArray_SimpleNew(1, &n, NPY_INT32);
  PyObject* appendix = PyList_New(0);
  if (slots_arr == nullptr || appendix == nullptr) {
    Py_XDECREF(slots_arr); Py_XDECREF(appendix); Py_DECREF(fast);
    return nullptr;
  }
  int32_t* slots = (int32_t*)PyArray_DATA((PyArrayObject*)slots_arr);

  // pass 2 — assign slots: pointer-identity hit (interned repeats), byte
  // hit, or new slot + appendix entry (normalized key object). The
  // appendix append runs BEFORE the slot commits: an append failure (OOM)
  // must not leave a slot the Python source of truth never hears about
  // (the no-mutate-on-failure contract ops/keytable.py assumes — a
  // mutated-but-unreported table would diverge the mirror forever).
  const int64_t n0 = kt->n;  // rollback floor: slots committed this call
  bool fail = false;
  for (npy_intp i = 0; i < n && !fail; i++) {
    PyObject* it = items[i];
    auto pit = kt->ptr_cache.find(it);
    if (pit != kt->ptr_cache.end()) {
      slots[i] = pit->second;
      continue;
    }
    StrKey key{spans[(size_t)i].first, (size_t)spans[(size_t)i].second};
    auto bit = kt->byte_map.find(key);
    int32_t slot;
    if (bit != kt->byte_map.end()) {
      slot = bit->second;
    } else {
      // appendix carries the NORMALIZED key ("" for None, else the raw
      // string object) in first-seen order — feeding exactly this
      // sequence to a Python KeyTable assigns identical ids
      if (it == Py_None) {
        PyObject* empty = PyUnicode_FromStringAndSize("", 0);
        if (empty == nullptr || PyList_Append(appendix, empty) < 0) {
          Py_XDECREF(empty);
          fail = true;
          break;
        }
        Py_DECREF(empty);
      } else if (PyList_Append(appendix, it) < 0) {
        fail = true;
        break;
      }
      slot = (int32_t)kt->n++;
      kt->storage.emplace_back(key.p, key.n);
      const std::string& owned = kt->storage.back();
      kt->byte_map.emplace(StrKey{owned.data(), owned.size()}, slot);
    }
    slots[i] = slot;
    if (kt->ptr_cache.size() < kPtrCacheCap) {
      Py_INCREF(it);
      kt->ptr_cache.emplace(it, slot);
    }
  }
  Py_DECREF(fast);
  if (fail) {
    // mid-batch failure: EARLIER rows of this call may have committed
    // slots whose appendix will now never reach the Python table — roll
    // every slot >= n0 back out of storage/byte_map/n, and evict
    // ptr_cache entries pointing at them (a stale pointer hit would
    // otherwise resurrect a slot id the table no longer assigns)
    while (kt->n > n0) {
      const std::string& owned = kt->storage.back();
      kt->byte_map.erase(StrKey{owned.data(), owned.size()});
      kt->storage.pop_back();
      kt->n--;
    }
    for (auto itc = kt->ptr_cache.begin(); itc != kt->ptr_cache.end();) {
      if (itc->second >= n0) {
        Py_DECREF(itc->first);
        itc = kt->ptr_cache.erase(itc);
      } else {
        ++itc;
      }
    }
    Py_DECREF(slots_arr);
    Py_DECREF(appendix);
    return nullptr;
  }
  PyObject* out = PyTuple_Pack(2, slots_arr, appendix);
  Py_DECREF(slots_arr);
  Py_DECREF(appendix);
  return out;
}

PyMethodDef methods[] = {
    {"decode", jc_decode, METH_VARARGS,
     "decode(payloads, fields, shards=1) -> (columns, valid, bad)"},
    {"keytab_new", kt_new, METH_NOARGS,
     "keytab_new() -> persistent key-slot table capsule"},
    {"keytab_encode", kt_encode, METH_VARARGS,
     "keytab_encode(tab, keys) -> (slots int32, appendix list)"},
    {"keytab_len", kt_len, METH_VARARGS, "keytab_len(tab) -> int"},
    {"keytab_clear", kt_clear, METH_VARARGS, "keytab_clear(tab)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "ekjsoncol",
    "native columnar JSON decoder", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit_ekjsoncol(void) {
  import_array();
  PyObject* m = PyModule_Create(&moduledef);
  if (m == nullptr) return nullptr;
  FallbackError = PyErr_NewException("ekjsoncol.Fallback", nullptr, nullptr);
  Py_INCREF(FallbackError);
  PyModule_AddObject(m, "Fallback", FallbackError);
  return m;
}
