// Package connection implements the worker side of the engine's framed IPC
// transport (docs/PLUGIN_WIRE_PROTOCOL.md): every message is one frame of
// uint32 little-endian payload length followed by the payload, carried over
// a unix domain socket. The engine always LISTENS and the worker always
// DIALS, for all three channel roles:
//
//	PAIR      control + function channels (strict request/reply)
//	PUSH      source data channel (worker -> engine, send-only)
//	PULL      sink data channel (engine -> worker, receive-only)
//
// Role analogue of the reference SDK's connection package
// (/root/reference/sdk/go/connection/connection.go), which wraps nanomsg;
// this transport needs only the stdlib.
package connection

import (
	"encoding/binary"
	"errors"
	"fmt"
	"io"
	"net"
	"os"
	"path/filepath"
	"strings"
	"time"
)

// ErrClosed is returned after Close, or when the engine hangs up.
var ErrClosed = errors.New("ekipc: connection closed")

// RuntimeDir resolves the engine's per-instance socket directory exactly as
// the engine does (ekuiper_tpu/plugin/ipc.py _ipc_dir): the
// EKUIPER_TPU_RUNTIME_DIR env var if set, else /tmp/ektpu_<ns> where <ns>
// is EKUIPER_TPU_IPC_NS (exported to the worker process by the engine).
func RuntimeDir() string {
	if d := os.Getenv("EKUIPER_TPU_RUNTIME_DIR"); d != "" {
		return d
	}
	ns := os.Getenv("EKUIPER_TPU_IPC_NS")
	if ns == "" {
		ns = fmt.Sprint(os.Getpid())
	}
	return filepath.Join("/tmp", "ektpu_"+ns)
}

// URL builds the channel url for a named channel: ipc://<dir>/<name>.ipc.
func URL(name string) string {
	return "ipc://" + filepath.Join(RuntimeDir(), name+".ipc")
}

// SocketPath extracts the filesystem path from an ipc:// url.
func SocketPath(url string) string {
	return strings.TrimPrefix(url, "ipc://")
}

// Conn is one framed channel. The PAIR/PUSH/PULL discipline is enforced by
// the caller (runtime package); the frame format is identical for all roles.
type Conn struct {
	c net.Conn
	// partial buffers bytes of an incomplete frame across Recv deadlines —
	// a read that straddles a timeout must not lose already-consumed bytes
	// or the stream desyncs (the engine's ipc layer buffers the same way).
	partial []byte
	chunk   []byte // reusable read buffer (Recv polls every 500ms when idle)
}

// Dial connects to an ipc:// url, retrying until timeout so a worker that
// starts before the engine finishes binding the endpoint still connects.
func Dial(url string, timeout time.Duration) (*Conn, error) {
	path := SocketPath(url)
	deadline := time.Now().Add(timeout)
	for {
		c, err := net.DialTimeout("unix", path, time.Second)
		if err == nil {
			return &Conn{c: c}, nil
		}
		if time.Now().After(deadline) {
			return nil, fmt.Errorf("ekipc: dial %s: %w", url, err)
		}
		time.Sleep(50 * time.Millisecond)
	}
}

// Send writes one frame: 4-byte little-endian length, then the payload.
func (c *Conn) Send(payload []byte) error {
	var hdr [4]byte
	binary.LittleEndian.PutUint32(hdr[:], uint32(len(payload)))
	if _, err := c.c.Write(hdr[:]); err != nil {
		return c.mapErr(err)
	}
	_, err := c.c.Write(payload)
	return c.mapErr(err)
}

// Recv reads one frame, blocking up to timeout (0 = block forever).
// Returns os.ErrDeadlineExceeded on timeout, ErrClosed on engine hangup.
// A timeout mid-frame is safe: the bytes read so far stay buffered and the
// next Recv resumes the same frame.
func (c *Conn) Recv(timeout time.Duration) ([]byte, error) {
	if timeout > 0 {
		_ = c.c.SetReadDeadline(time.Now().Add(timeout))
		defer c.c.SetReadDeadline(time.Time{})
	}
	for {
		if len(c.partial) >= 4 {
			n := int(binary.LittleEndian.Uint32(c.partial[:4]))
			if len(c.partial) >= 4+n {
				payload := make([]byte, n)
				copy(payload, c.partial[4:4+n])
				c.partial = append(c.partial[:0], c.partial[4+n:]...)
				return payload, nil
			}
		}
		if c.chunk == nil {
			c.chunk = make([]byte, 64*1024)
		}
		k, err := c.c.Read(c.chunk)
		if k > 0 {
			c.partial = append(c.partial, c.chunk[:k]...)
		}
		if err != nil {
			return nil, c.mapErr(err)
		}
	}
}

func (c *Conn) mapErr(err error) error {
	if err == nil {
		return nil
	}
	if errors.Is(err, io.EOF) || errors.Is(err, io.ErrUnexpectedEOF) ||
		errors.Is(err, net.ErrClosed) {
		return ErrClosed
	}
	return err
}

func (c *Conn) Close() error { return c.c.Close() }
