module github.com/ekuiper-tpu/sdk-go

go 1.21
