package main

import (
	"encoding/json"
	"errors"
	"os"

	"github.com/ekuiper-tpu/sdk-go/api"
)

// fileSink appends every collected payload as one JSON line to the file
// named by the "path" prop.
type fileSink struct {
	path string
	f    *os.File
}

func (k *fileSink) Configure(props map[string]interface{}) error {
	p, _ := props["path"].(string)
	if p == "" {
		return errors.New("file sink requires a \"path\" property")
	}
	k.path = p
	return nil
}

func (k *fileSink) Open(_ api.StreamContext) error {
	f, err := os.OpenFile(k.path, os.O_CREATE|os.O_WRONLY|os.O_APPEND, 0o644)
	if err != nil {
		return err
	}
	k.f = f
	return nil
}

func (k *fileSink) Collect(_ api.StreamContext, data interface{}) error {
	b, err := json.Marshal(data)
	if err != nil {
		return err
	}
	_, err = k.f.Write(append(b, '\n'))
	return err
}

func (k *fileSink) Close(_ api.StreamContext) error {
	if k.f != nil {
		return k.f.Close()
	}
	return nil
}
