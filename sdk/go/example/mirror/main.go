// Command mirror is the reference example plugin for the Go SDK: an echo
// function, a ticking random source, and a line-appending file sink — the
// same symbol set the reference SDK's example ships
// (/root/reference/sdk/go/example/mirror/), served over this engine's
// framed unix-socket protocol.
//
// Build:   go build -o mirror .
// Install: descriptor mirror.json with "language": "binary".
package main

import (
	"log"

	"github.com/ekuiper-tpu/sdk-go/api"
	"github.com/ekuiper-tpu/sdk-go/runtime"
)

func main() {
	err := runtime.Start(runtime.PluginConfig{
		Name: "mirror",
		Functions: map[string]runtime.NewFunctionFunc{
			"echo": func() api.Function { return &echoFunc{} },
		},
		Sources: map[string]runtime.NewSourceFunc{
			"random": func() api.Source { return &randomSource{} },
		},
		Sinks: map[string]runtime.NewSinkFunc{
			"file": func() api.Sink { return &fileSink{} },
		},
	})
	if err != nil {
		log.Fatal(err)
	}
}
