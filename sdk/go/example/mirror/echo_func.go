package main

import (
	"errors"

	"github.com/ekuiper-tpu/sdk-go/api"
)

// echoFunc mirrors its single argument back — the smallest possible
// function symbol, used by the golden-fixture interop test.
type echoFunc struct{}

func (f *echoFunc) Validate(args []interface{}) error {
	if len(args) != 1 {
		return errors.New("echo takes exactly 1 argument")
	}
	return nil
}

func (f *echoFunc) Exec(args []interface{}, _ api.FunctionContext) (interface{}, bool) {
	if len(args) != 1 {
		return "echo takes exactly 1 argument", false
	}
	return args[0], true
}

func (f *echoFunc) IsAggregate() bool { return false }

func (f *echoFunc) Close(_ api.StreamContext) error { return nil }
