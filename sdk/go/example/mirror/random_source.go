package main

import (
	"math/rand"
	"time"

	"github.com/ekuiper-tpu/sdk-go/api"
)

// randomSource emits {"count": n, "value": r} every interval ms
// (default 1000, prop "interval").
type randomSource struct {
	interval time.Duration
}

func (s *randomSource) Configure(_ string, props map[string]interface{}) error {
	s.interval = time.Second
	if v, ok := props["interval"].(float64); ok && v > 0 {
		s.interval = time.Duration(v) * time.Millisecond
	}
	return nil
}

func (s *randomSource) Open(ctx api.StreamContext, consumer chan<- api.SourceTuple, _ chan<- error) {
	tick := time.NewTicker(s.interval)
	defer tick.Stop()
	count := 0
	for {
		select {
		case <-ctx.Done():
			return
		case <-tick.C:
			count++
			t := api.NewDefaultSourceTuple(map[string]interface{}{
				"count": count,
				"value": rand.Float64(),
			}, nil)
			select {
			case consumer <- t:
			case <-ctx.Done(): // never block a stopped symbol on a full buffer
				return
			}
		}
	}
}

func (s *randomSource) Close(_ api.StreamContext) error { return nil }
