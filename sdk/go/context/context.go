// Package context implements api.StreamContext / api.FunctionContext for
// the plugin-side runtime (role analogue of the reference SDK's context
// package; built on the stdlib log package instead of logrus — no deps).
package context

import (
	gocontext "context"
	"fmt"
	"log"
	"os"

	"github.com/ekuiper-tpu/sdk-go/api"
)

// LogLevel gates stdoutLogger output; set from the EKUIPER_TPU_LOG_LEVEL
// env var ("debug" | "info" | "warn" | "error", default info).
var LogLevel = func() int {
	switch os.Getenv("EKUIPER_TPU_LOG_LEVEL") {
	case "debug":
		return 0
	case "warn":
		return 2
	case "error":
		return 3
	default:
		return 1
	}
}()

type stdoutLogger struct{ prefix string }

func (l *stdoutLogger) out(level int, tag string, args ...interface{}) {
	if level >= LogLevel {
		log.Print(tag, " ", l.prefix, " ", fmt.Sprintln(args...))
	}
}

func (l *stdoutLogger) outf(level int, tag, format string, args ...interface{}) {
	if level >= LogLevel {
		log.Printf("%s %s %s", tag, l.prefix, fmt.Sprintf(format, args...))
	}
}

func (l *stdoutLogger) Debug(args ...interface{}) { l.out(0, "DEBUG", args...) }
func (l *stdoutLogger) Info(args ...interface{})  { l.out(1, "INFO", args...) }
func (l *stdoutLogger) Warn(args ...interface{})  { l.out(2, "WARN", args...) }
func (l *stdoutLogger) Error(args ...interface{}) { l.out(3, "ERROR", args...) }
func (l *stdoutLogger) Debugf(f string, args ...interface{}) {
	l.outf(0, "DEBUG", f, args...)
}
func (l *stdoutLogger) Infof(f string, args ...interface{}) {
	l.outf(1, "INFO", f, args...)
}
func (l *stdoutLogger) Warnf(f string, args ...interface{}) {
	l.outf(2, "WARN", f, args...)
}
func (l *stdoutLogger) Errorf(f string, args ...interface{}) {
	l.outf(3, "ERROR", f, args...)
}

type defaultContext struct {
	gocontext.Context
	ruleId     string
	opId       string
	instanceId int
	logger     api.Logger
}

// Background returns the root plugin context.
func Background() api.StreamContext {
	return &defaultContext{
		Context: gocontext.Background(),
		logger:  &stdoutLogger{prefix: "[plugin]"},
	}
}

func (c *defaultContext) GetLogger() api.Logger { return c.logger }
func (c *defaultContext) GetRuleId() string     { return c.ruleId }
func (c *defaultContext) GetOpId() string       { return c.opId }
func (c *defaultContext) GetInstanceId() int    { return c.instanceId }

func (c *defaultContext) WithMeta(ruleId, opId string) api.StreamContext {
	next := *c
	next.ruleId, next.opId = ruleId, opId
	next.logger = &stdoutLogger{prefix: fmt.Sprintf("[%s/%s]", ruleId, opId)}
	return &next
}

func (c *defaultContext) WithInstance(instanceId int) api.StreamContext {
	next := *c
	next.instanceId = instanceId
	return &next
}

func (c *defaultContext) WithCancel() (api.StreamContext, gocontext.CancelFunc) {
	next := *c
	inner, cancel := gocontext.WithCancel(c.Context)
	next.Context = inner
	return &next, cancel
}

type funcContext struct {
	api.StreamContext
	funcId int
}

// NewFuncContext wraps a stream context with a function call-site id.
func NewFuncContext(ctx api.StreamContext, funcId int) api.FunctionContext {
	return &funcContext{StreamContext: ctx, funcId: funcId}
}

func (c *funcContext) GetFuncId() int { return c.funcId }
