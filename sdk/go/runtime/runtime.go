// Package runtime is the worker-side main loop of the portable plugin
// protocol (docs/PLUGIN_WIRE_PROTOCOL.md) — the Go analogue of the Python
// SDK's plugin_main (ekuiper_tpu/sdk/runtime.py) and role analogue of the
// reference SDK's runtime package (/root/reference/sdk/go/runtime/).
//
// Lifecycle: dial the engine's control channel plugin_<name>, send the
// handshake, then serve start/stop/ping commands. Every started symbol gets
// its own goroutine and its own data channel:
//
//	function  PAIR  dial func_<symbol>; loop {"func","args"} -> {"state","result"}
//	source    PUSH  dial source_<ruleId>_<opId>_<instanceId>; push JSON tuples
//	sink      PULL  dial sink_<ruleId>_<opId>_<instanceId>; recv rows -> Collect
package runtime

import (
	"encoding/json"
	"errors"
	"fmt"
	"os"
	"sync"
	"time"

	"github.com/ekuiper-tpu/sdk-go/api"
	"github.com/ekuiper-tpu/sdk-go/connection"
	sdkcontext "github.com/ekuiper-tpu/sdk-go/context"
)

// NewXFunc factories let the runtime build a fresh symbol instance per
// start command (matching the Python SDK, which instantiates per start).
type (
	NewSourceFunc   func() api.Source
	NewFunctionFunc func() api.Function
	NewSinkFunc     func() api.Sink
)

// PluginConfig declares the symbols this worker serves. Name must match the
// descriptor json the engine installed.
type PluginConfig struct {
	Name      string
	Sources   map[string]NewSourceFunc
	Functions map[string]NewFunctionFunc
	Sinks     map[string]NewSinkFunc
}

// wire message shapes; field order here defines the marshaled byte layout
// the golden fixtures in tests/fixtures/go_sdk/ pin down.
type handshake struct {
	Status string `json:"status"`
	Name   string `json:"name"`
	Pid    int    `json:"pid"`
}

type command struct {
	Cmd  string  `json:"cmd"`
	Ctrl control `json:"ctrl"`
}

type control struct {
	SymbolName string                 `json:"symbolName"`
	PluginType string                 `json:"pluginType"`
	DataSource string                 `json:"dataSource"`
	Config     map[string]interface{} `json:"config"`
	Meta       meta                   `json:"meta"`
}

type meta struct {
	RuleId     string `json:"ruleId"`
	OpId       string `json:"opId"`
	InstanceId int    `json:"instanceId"`
}

type reply struct {
	State  string      `json:"state"`
	Result interface{} `json:"result,omitempty"`
}

type funcCall struct {
	Func string            `json:"func"`
	Args []json.RawMessage `json:"args"`
}

func okReply() []byte {
	b, _ := json.Marshal(reply{State: "ok"})
	return b
}

func errReply(msg string) []byte {
	b, _ := json.Marshal(reply{State: "error", Result: msg})
	return b
}

// runner is one live symbol instance.
type runner struct {
	stop func()
}

// runnerKey must match the engine's start/stop pairing: symbol name plus
// the canonical (sorted-key) JSON of the meta object.
func runnerKey(sym string, m meta) string {
	canon, _ := json.Marshal(map[string]interface{}{
		"ruleId": m.RuleId, "opId": m.OpId, "instanceId": m.InstanceId,
	}) // Go marshals map keys sorted — canonical by construction
	return sym + ":" + string(canon)
}

// Start serves the plugin until the engine closes the control channel.
// It blocks; call it from main().
func Start(cfg PluginConfig) error {
	ctrlConn, err := connection.Dial(
		connection.URL("plugin_"+cfg.Name), 15*time.Second)
	if err != nil {
		return err
	}
	defer ctrlConn.Close()
	hs, _ := json.Marshal(handshake{Status: "ok", Name: cfg.Name, Pid: os.Getpid()})
	if err := ctrlConn.Send(hs); err != nil {
		return err
	}

	root := sdkcontext.Background()
	logger := root.GetLogger()
	runners := map[string]*runner{}
	var mu sync.Mutex
	defer func() {
		mu.Lock()
		for _, r := range runners {
			r.stop()
		}
		mu.Unlock()
	}()

	for {
		raw, err := ctrlConn.Recv(time.Second)
		if errors.Is(err, os.ErrDeadlineExceeded) {
			continue
		}
		if err != nil {
			if errors.Is(err, connection.ErrClosed) {
				return nil // engine shut down — normal exit
			}
			return err
		}
		var cmd command
		if err := json.Unmarshal(raw, &cmd); err != nil {
			_ = ctrlConn.Send(errReply(fmt.Sprintf("bad command: %v", err)))
			continue
		}
		key := runnerKey(cmd.Ctrl.SymbolName, cmd.Ctrl.Meta)
		switch cmd.Cmd {
		case "start":
			r, err := startSymbol(cfg, cmd.Ctrl, root)
			if err != nil {
				logger.Errorf("start %s: %v", cmd.Ctrl.SymbolName, err)
				_ = ctrlConn.Send(errReply(err.Error()))
				continue
			}
			mu.Lock()
			runners[key] = r
			mu.Unlock()
			_ = ctrlConn.Send(okReply())
		case "stop":
			mu.Lock()
			r := runners[key]
			delete(runners, key)
			mu.Unlock()
			if r != nil {
				r.stop()
			}
			_ = ctrlConn.Send(okReply())
		case "ping":
			_ = ctrlConn.Send(okReply())
		default:
			_ = ctrlConn.Send(errReply("unknown cmd " + cmd.Cmd))
		}
	}
}

func startSymbol(cfg PluginConfig, ctrl control, root api.StreamContext) (*runner, error) {
	sym := ctrl.SymbolName
	ctx := root.WithMeta(ctrl.Meta.RuleId, ctrl.Meta.OpId).
		WithInstance(ctrl.Meta.InstanceId)
	switch ctrl.PluginType {
	case "function":
		nf := cfg.Functions[sym]
		if nf == nil {
			return nil, fmt.Errorf("symbol %s not found in plugin %s", sym, cfg.Name)
		}
		return runFunction(sym, nf(), ctx)
	case "source":
		ns := cfg.Sources[sym]
		if ns == nil {
			return nil, fmt.Errorf("symbol %s not found in plugin %s", sym, cfg.Name)
		}
		return runSource(ctrl, ns(), ctx)
	case "sink":
		nk := cfg.Sinks[sym]
		if nk == nil {
			return nil, fmt.Errorf("symbol %s not found in plugin %s", sym, cfg.Name)
		}
		return runSink(ctrl, nk(), ctx)
	}
	return nil, fmt.Errorf("unknown pluginType %q", ctrl.PluginType)
}

// dataURL derives the data channel name for a source/sink symbol.
func dataURL(kind string, m meta) string {
	return connection.URL(fmt.Sprintf("%s_%s_%s_%d", kind, m.RuleId, m.OpId, m.InstanceId))
}

// ---------------------------------------------------------------- function

func runFunction(sym string, f api.Function, sctx api.StreamContext) (*runner, error) {
	conn, err := connection.Dial(connection.URL("func_"+sym), 10*time.Second)
	if err != nil {
		return nil, err
	}
	ctx, cancel := sctx.WithCancel()
	fctx := sdkcontext.NewFuncContext(ctx, 0)
	go func() {
		defer conn.Close()
		for ctx.Err() == nil {
			raw, err := conn.Recv(500 * time.Millisecond)
			if errors.Is(err, os.ErrDeadlineExceeded) {
				continue
			}
			if err != nil {
				return
			}
			var call funcCall
			var resp []byte
			if err := json.Unmarshal(raw, &call); err != nil {
				resp = errReply(fmt.Sprintf("bad request: %v", err))
			} else {
				resp = dispatchFunc(f, &call, fctx)
			}
			if err := conn.Send(resp); err != nil {
				return
			}
		}
	}()
	return &runner{stop: func() {
		cancel()
		conn.Close()
		_ = f.Close(sctx)
	}}, nil
}

func dispatchFunc(f api.Function, call *funcCall, fctx api.FunctionContext) []byte {
	decode := func(raws []json.RawMessage) []interface{} {
		out := make([]interface{}, len(raws))
		for i, r := range raws {
			_ = json.Unmarshal(r, &out[i])
		}
		return out
	}
	switch call.Func {
	case "Validate":
		if err := f.Validate(decode(call.Args)); err != nil {
			return errReply(err.Error())
		}
		b, _ := json.Marshal(reply{State: "ok", Result: ""})
		return b
	case "Exec":
		args := call.Args
		if len(args) > 0 {
			args = args[:len(args)-1] // engine appends the call context
		}
		res, ok := f.Exec(decode(args), fctx)
		if !ok {
			return errReply(fmt.Sprint(res))
		}
		b, err := json.Marshal(reply{State: "ok", Result: res})
		if err != nil {
			return errReply(fmt.Sprintf("unserializable result: %v", err))
		}
		return b
	case "IsAggregate":
		b, _ := json.Marshal(reply{State: "ok", Result: f.IsAggregate()})
		return b
	}
	return errReply("unknown func " + call.Func)
}

// ------------------------------------------------------------------ source

func runSource(ctrl control, s api.Source, sctx api.StreamContext) (*runner, error) {
	if err := s.Configure(ctrl.DataSource, ctrl.Config); err != nil {
		return nil, err
	}
	conn, err := connection.Dial(dataURL("source", ctrl.Meta), 10*time.Second)
	if err != nil {
		return nil, err
	}
	ctx, cancel := sctx.WithCancel()
	consumer := make(chan api.SourceTuple, 64)
	errCh := make(chan error, 1)
	go s.Open(ctx, consumer, errCh)
	go func() {
		defer conn.Close()
		defer cancel() // tear the symbol down on any exit path so Open stops
		for {
			select {
			case <-ctx.Done():
				return
			case err := <-errCh:
				ctx.GetLogger().Errorf("source %s: %v", ctrl.SymbolName, err)
				return
			case t := <-consumer:
				b, err := json.Marshal(t.Message())
				if err != nil {
					ctx.GetLogger().Errorf("source %s: unserializable tuple: %v",
						ctrl.SymbolName, err)
					continue
				}
				if err := conn.Send(b); err != nil {
					return
				}
			}
		}
	}()
	return &runner{stop: func() {
		cancel()
		conn.Close()
		_ = s.Close(sctx)
	}}, nil
}

// -------------------------------------------------------------------- sink

func runSink(ctrl control, k api.Sink, sctx api.StreamContext) (*runner, error) {
	if err := k.Configure(ctrl.Config); err != nil {
		return nil, err
	}
	conn, err := connection.Dial(dataURL("sink", ctrl.Meta), 10*time.Second)
	if err != nil {
		return nil, err
	}
	ctx, cancel := sctx.WithCancel()
	if err := k.Open(ctx); err != nil {
		cancel()
		conn.Close()
		return nil, err
	}
	go func() {
		defer conn.Close()
		for ctx.Err() == nil {
			raw, err := conn.Recv(500 * time.Millisecond)
			if errors.Is(err, os.ErrDeadlineExceeded) {
				continue
			}
			if err != nil {
				return
			}
			var data interface{}
			if err := json.Unmarshal(raw, &data); err != nil {
				ctx.GetLogger().Errorf("sink %s: bad payload: %v", ctrl.SymbolName, err)
				continue
			}
			if err := k.Collect(ctx, data); err != nil {
				ctx.GetLogger().Errorf("sink %s: collect: %v", ctrl.SymbolName, err)
			}
		}
	}()
	return &runner{stop: func() {
		cancel()
		conn.Close()
		_ = k.Close(sctx)
	}}, nil
}
