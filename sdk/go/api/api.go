// Package api defines the contract a portable plugin implements to serve
// functions, sources, and sinks to the ekuiper_tpu engine.
//
// Role analogue of the reference SDK's api package
// (/root/reference/sdk/go/api/api.go); the interface shapes match so plugin
// code ports with minimal edits, but the transport underneath is this
// engine's framed unix-socket protocol (docs/PLUGIN_WIRE_PROTOCOL.md), not
// nanomsg — this SDK has zero third-party dependencies.
package api

import "context"

// SourceTuple is one record emitted by a Source: a message payload plus
// out-of-band metadata. On the wire only the message is sent (the engine's
// decode pipeline attaches its own meta); Meta is available for plugin-side
// bookkeeping.
type SourceTuple interface {
	Message() map[string]interface{}
	Meta() map[string]interface{}
}

// DefaultSourceTuple is the plain struct implementation of SourceTuple.
type DefaultSourceTuple struct {
	Mess map[string]interface{} `json:"message"`
	M    map[string]interface{} `json:"meta"`
}

func NewDefaultSourceTuple(message, meta map[string]interface{}) *DefaultSourceTuple {
	return &DefaultSourceTuple{Mess: message, M: meta}
}

func (t *DefaultSourceTuple) Message() map[string]interface{} { return t.Mess }
func (t *DefaultSourceTuple) Meta() map[string]interface{}    { return t.M }

// Source pushes records into the engine. Open runs the ingest loop
// synchronously; the runtime calls it on its own goroutine. Emit tuples on
// consumer; report a fatal ingest failure on errCh (the runtime logs it and
// tears the symbol down). Return when ctx is done.
type Source interface {
	Configure(datasource string, props map[string]interface{}) error
	Open(ctx StreamContext, consumer chan<- SourceTuple, errCh chan<- error)
	Closable
}

// Function serves a SQL scalar or aggregate function. Exec returns the
// result value and true, or an error value and false (the engine surfaces
// it as a rule error). For aggregate functions every argument arrives as a
// slice of the group's values.
type Function interface {
	Validate(args []interface{}) error
	Exec(args []interface{}, ctx FunctionContext) (interface{}, bool)
	IsAggregate() bool
}

// Sink receives result rows from the engine. Collect is called once per
// delivered payload — a map for single rows, []map for window batches.
type Sink interface {
	Configure(props map[string]interface{}) error
	Open(ctx StreamContext) error
	Collect(ctx StreamContext, data interface{}) error
	Closable
}

type Closable interface {
	Close(ctx StreamContext) error
}

// Logger is the leveled logger handed to plugin code via the context.
type Logger interface {
	Debug(args ...interface{})
	Info(args ...interface{})
	Warn(args ...interface{})
	Error(args ...interface{})
	Debugf(format string, args ...interface{})
	Infof(format string, args ...interface{})
	Warnf(format string, args ...interface{})
	Errorf(format string, args ...interface{})
}

// StreamContext carries the rule/op/instance identity of the symbol
// invocation plus cancellation, mirroring the engine-side operator context
// (ekuiper_tpu/functions/context.py).
type StreamContext interface {
	context.Context
	GetLogger() Logger
	GetRuleId() string
	GetOpId() string
	GetInstanceId() int
	WithMeta(ruleId, opId string) StreamContext
	WithInstance(instanceId int) StreamContext
	WithCancel() (StreamContext, context.CancelFunc)
}

// FunctionContext additionally identifies which function call site within
// the rule is executing.
type FunctionContext interface {
	StreamContext
	GetFuncId() int
}
